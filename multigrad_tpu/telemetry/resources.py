"""Per-process resource monitor: memory, duty cycle, compile truth.

The paper's claim is that comm cost is O(|sumstats|+|params|)
independent of data size — which makes *device residency* the binding
resource for a serve fleet: how full the accelerator's memory is, how
busy the dispatch loop is, and how much wall time disappears into XLA
builds.  PR 14's memory model predicts the first, nothing measured
any of them.  :class:`ResourceMonitor` closes the gap with one
sampler thread per process:

* **host RSS** — ``/proc/self/statm`` resident pages × page size
  (``None`` off Linux: the monitor degrades, never raises);
* **device memory** — ``device.memory_stats()`` where the backend
  implements it (TPU/GPU: ``bytes_in_use`` / ``peak_bytes_in_use`` /
  ``bytes_limit``); absent or exotic backends yield ``None`` fields
  plus a one-shot ``resource_monitor_degraded`` telemetry note;
* **busy/idle duty cycle** — the serve scheduler brackets every
  bucket dispatch with :meth:`dispatch_enter` / :meth:`dispatch_exit`
  (or the :meth:`dispatching` context manager); each sample folds the
  busy seconds accumulated since the previous sample into a window
  ``busy_frac`` — the "sustained idle occupancy" signal the ROADMAP's
  elastic autoscaler is specified to scale in on;
* **compile accounting** — program count and cache hit/miss observed
  at the single program-cache boundary every compiled program in the
  package passes through (:func:`multigrad_tpu.utils.util
  .cached_program`, via :func:`~multigrad_tpu.utils.util
  .add_compile_observer`); cumulative compile *seconds* from the
  ``jax.monitoring`` ``backend_compile_duration`` events (real XLA
  wall time — programs compile lazily at first call, so timing the
  cache boundary alone would read ~0), falling back to build-thunk
  wall time where ``jax.monitoring`` is unavailable.  The totals are
  process-global: programs built before the monitor started still
  count.

Samples land in a bounded ring (:meth:`ring` — what flight/postmortem
bundles capture), export as ``multigrad_resource_*`` gauges through a
:class:`~multigrad_tpu.telemetry.LiveMetrics` registry, and every
``emit_every``-th sample is written as a ``resource_sample`` record
through the logger — so a :class:`~multigrad_tpu.telemetry
.FlightRecorder` sink's ring holds the recent resource history at
dump time without any extra wiring.

:func:`autoscaler_inputs` publishes the documented scale-out/scale-in
contract in one place: ``busy_frac``, ``queue_wait_p95_s`` (from the
hop histograms the tracing layer already records) and measured
``headroom_bytes`` (device limit minus measured peak; host RSS is
reported but deliberately not a headroom input — the host is not the
binding resource).

Memory truth closes the loop in the serve scheduler: after each
bucket dispatch it compares the measured device peak against the
PR-14 model (:func:`measured_vs_modeled`) and emits the record the
bench/regress gate tracks, so the model can never silently drift from
the hardware.

This module imports only stdlib at module level (jax lazily inside
the device probe), per the telemetry package contract.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Optional

from .._lockdep import make_lock

__all__ = ["ResourceMonitor", "read_rss_bytes", "device_memory",
           "compile_totals", "reset_compile_totals",
           "autoscaler_inputs", "measured_vs_modeled",
           "SNAPSHOT_KEYS"]

#: The compact over-the-wire snapshot schema (the heartbeat payload
#: and the known-keys contract of ``serve.wire.resources_from_wire``).
SNAPSHOT_KEYS = ("t", "uptime_s", "rss_bytes", "device_bytes_in_use",
                 "device_peak_bytes", "device_bytes_limit",
                 "busy_frac", "busy_s_total", "compile_count",
                 "compile_s_total", "compile_hits", "compile_misses")


# ------------------------------------------------------------------ #
# process-global compile accounting (fed by the program-cache
# boundary in utils.util; plain-lock guarded, registered lazily so a
# process that never monitors pays nothing)
# ------------------------------------------------------------------ #
_COMPILE_LOCK = threading.Lock()
_COMPILE = {"count": 0, "seconds": 0.0, "hits": 0, "misses": 0}
_observer_installed = False


_monitoring_ok = False


def _compile_observer(key, seconds, hit):
    with _COMPILE_LOCK:
        if hit:
            _COMPILE["hits"] += 1
        else:
            _COMPILE["misses"] += 1
            _COMPILE["count"] += 1
            if not _monitoring_ok:
                # Fallback seconds source: the build-thunk wall time.
                # Usually ~0 (build returns an untraced jit wrapper;
                # XLA compiles lazily at first call) — the monitoring
                # listener below is the real source when available.
                _COMPILE["seconds"] += float(seconds)


def _jax_compile_listener(event, duration_s, **kwargs):
    # jax.monitoring fires this for every trace/lower/compile stage;
    # backend_compile_duration is the XLA wall time — the number an
    # operator means by "compile seconds".
    if event.endswith("backend_compile_duration"):
        with _COMPILE_LOCK:
            _COMPILE["seconds"] += float(duration_s)


def _install_observer():
    global _observer_installed, _monitoring_ok
    with _COMPILE_LOCK:
        if _observer_installed:
            return
        _observer_installed = True
    from ..utils.util import add_compile_observer
    add_compile_observer(_compile_observer)
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _jax_compile_listener)
        with _COMPILE_LOCK:
            _monitoring_ok = True
    except Exception:
        pass          # build-thunk fallback stays in force


def compile_totals() -> dict:
    """Process-global program-build accounting:
    ``{"count", "seconds", "hits", "misses"}`` (zeros until the first
    :class:`ResourceMonitor` installs the boundary observer)."""
    with _COMPILE_LOCK:
        return dict(_COMPILE)


def reset_compile_totals():
    """Zero the process-global compile counters (tests)."""
    with _COMPILE_LOCK:
        for k in _COMPILE:
            _COMPILE[k] = 0.0 if k == "seconds" else 0


# ------------------------------------------------------------------ #
# probes
# ------------------------------------------------------------------ #
def read_rss_bytes() -> Optional[int]:
    """Resident set size of this process from ``/proc/self/statm``
    (``None`` where procfs is absent — macOS, exotic containers)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return None


def device_memory(device=None) -> dict:
    """Device-memory fields from ``memory_stats()``, summed across
    local devices (or for one ``device``).

    Returns ``{"bytes_in_use", "peak_bytes", "bytes_limit",
    "supported"}`` — all three numbers ``None`` and ``supported``
    ``False`` when no local device implements ``memory_stats()``
    (the CPU backend) or jax is unavailable.  Never raises.
    """
    out = {"bytes_in_use": None, "peak_bytes": None,
           "bytes_limit": None, "supported": False}
    try:
        import jax
        devices = [device] if device is not None else jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not isinstance(stats, dict):
            continue
        for field, key in (("bytes_in_use", "bytes_in_use"),
                           ("peak_bytes", "peak_bytes_in_use"),
                           ("bytes_limit", "bytes_limit")):
            v = stats.get(key)
            if isinstance(v, (int, float)):
                out[field] = (out[field] or 0) + int(v)
                out["supported"] = True
    return out


def measured_vs_modeled(measured_peak_bytes, modeled_bytes) -> dict:
    """The memory-truth comparison the serve scheduler records per
    bucket dispatch: measured device peak against the PR-14 model.

    ``measured_ratio`` is measured/modeled (``None`` when the backend
    cannot measure — the regress gate treats nulls as warn-only, so a
    CPU round never flakes while a TPU round gates drift), and
    ``accuracy_frac`` is ``1 - |measured - modeled| / modeled`` —
    higher-better, so monotone regression gates catch drift in
    EITHER direction.
    """
    modeled = int(modeled_bytes) if modeled_bytes else None
    measured = int(measured_peak_bytes) \
        if isinstance(measured_peak_bytes, (int, float)) else None
    ratio = accuracy = None
    if measured is not None and modeled:
        ratio = round(measured / modeled, 4)
        accuracy = round(1.0 - abs(measured - modeled) / modeled, 4)
    return {"measured_peak_bytes": measured,
            "modeled_bytes": modeled,
            "measured_ratio": ratio,
            "accuracy_frac": accuracy}


class ResourceMonitor:
    """Per-process resource sampler (see the module docstring).

    Parameters
    ----------
    live : LiveMetrics or LiveServer, optional
        Registry to export ``multigrad_resource_*`` gauges into
        (a ``LiveMetrics``, or anything carrying one as
        ``.metrics`` — a ``LiveSink``/``LiveServer``).
    logger : MetricsLogger, optional
        Record stream for the periodic ``resource_sample`` records
        and the one-shot ``resource_monitor_degraded`` note.
    interval_s : float
        Sampling period.
    capacity : int
        Ring size (the "last K samples" a postmortem preserves).
    emit_every : int
        Every Nth sample is also logged as a ``resource_sample``
        record (0 disables record emission; the ring and gauges
        still update every sample).

    ``start()`` launches the daemon sampler thread; ``close()`` stops
    it and takes one final sample so the ring always holds the
    process's last known state.  All probe failures degrade to
    ``None`` fields — the monitor must never take down the fit it is
    watching.
    """

    def __init__(self, live=None, logger=None, interval_s: float = 0.5,
                 capacity: int = 256, emit_every: int = 20):
        self.live = getattr(live, "metrics", live)
        self.logger = logger
        self.interval_s = float(interval_s)
        self.emit_every = int(emit_every)
        self._ring = collections.deque(maxlen=int(capacity))
        # Sample assembly happens under the lock; gauge export and
        # record emission happen outside it (the registry and sinks
        # have their own locks).
        self._lock = make_lock(
            "telemetry.resources.ResourceMonitor._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.time()
        self._busy_total = 0.0        # cumulative dispatch seconds
        self._busy_depth = 0          # nested dispatch_enter count
        self._busy_since: Optional[float] = None
        self._prev_busy = 0.0         # busy_now at the previous sample
        self._prev_t: Optional[float] = None
        self._busy_frac: Optional[float] = None
        self._degraded_reported = False
        self._device_supported: Optional[bool] = None
        self._n_samples = 0
        _install_observer()

    # -- duty-cycle hooks (the serve scheduler brackets dispatches) --
    def dispatch_enter(self):
        """Mark device work started (re-entrant)."""
        now = time.monotonic()
        with self._lock:
            if self._busy_depth == 0:
                self._busy_since = now
            self._busy_depth += 1

    def dispatch_exit(self):
        """Mark device work finished."""
        now = time.monotonic()
        with self._lock:
            if self._busy_depth > 0:
                self._busy_depth -= 1
                if self._busy_depth == 0 and self._busy_since is not None:
                    self._busy_total += now - self._busy_since
                    self._busy_since = None

    class _Dispatching:
        __slots__ = ("monitor",)

        def __init__(self, monitor):
            self.monitor = monitor

        def __enter__(self):
            self.monitor.dispatch_enter()
            return self

        def __exit__(self, *exc):
            self.monitor.dispatch_exit()
            return False

    def dispatching(self):
        """Context manager bracketing one dispatch's device work."""
        return self._Dispatching(self)

    def _busy_now(self, now) -> float:
        # caller holds the lock
        busy = self._busy_total
        if self._busy_depth > 0 and self._busy_since is not None:
            busy += now - self._busy_since
        return busy

    @property
    def busy_seconds(self) -> float:
        """Cumulative dispatch-busy seconds so far."""
        with self._lock:
            return self._busy_now(time.monotonic())

    # -- sampling -----------------------------------------------------------
    def sample(self) -> dict:
        """Take one sample: probe, fold the busy window, append to
        the ring, export gauges, maybe emit a record.  Returns the
        sample dict.  Never raises."""
        try:
            return self._sample()
        except Exception as e:                       # degrade, never die
            self._note_degraded(f"sampler: {type(e).__name__}: {e}")
            return {}

    def _sample(self) -> dict:
        now_wall = time.time()
        now = time.monotonic()
        rss = read_rss_bytes()
        dev = device_memory()
        compile_ = compile_totals()
        first_unsupported = False
        with self._lock:
            if self._device_supported is None:
                self._device_supported = dev["supported"]
                first_unsupported = not dev["supported"]
            busy_now = self._busy_now(now)
            if self._prev_t is not None and now > self._prev_t:
                frac = (busy_now - self._prev_busy) \
                    / (now - self._prev_t)
                self._busy_frac = round(min(max(frac, 0.0), 1.0), 4)
            self._prev_t = now
            self._prev_busy = busy_now
            self._n_samples += 1
            n = self._n_samples
            sample = {
                "event": "resource_sample",
                "t": now_wall,
                "uptime_s": round(now_wall - self._t_start, 3),
                "rss_bytes": rss,
                "device_bytes_in_use": dev["bytes_in_use"],
                "device_peak_bytes": dev["peak_bytes"],
                "device_bytes_limit": dev["bytes_limit"],
                "busy_frac": self._busy_frac,
                "busy_s_total": round(busy_now, 4),
                "compile_count": compile_["count"],
                "compile_s_total": round(compile_["seconds"], 4),
                "compile_hits": compile_["hits"],
                "compile_misses": compile_["misses"],
            }
            self._ring.append(sample)
        if first_unsupported:
            # Outside the lock: _note_degraded takes it again.
            self._note_degraded("device memory_stats unavailable "
                                "(CPU or exotic backend); device "
                                "fields will be null")
        self._export(sample)
        if self.logger is not None and self.emit_every \
                and (n - 1) % self.emit_every == 0:
            try:
                self.logger.log("resource_sample",
                                **{k: v for k, v in sample.items()
                                   if k not in ("event", "t")})
            except Exception:
                pass
        return sample

    def _export(self, sample: dict):
        lm = self.live
        if lm is None:
            return
        gauges = (
            ("multigrad_resource_rss_bytes",
             sample["rss_bytes"], "Host resident set size (bytes)."),
            ("multigrad_resource_device_bytes_in_use",
             sample["device_bytes_in_use"],
             "Device memory in use, summed over local devices."),
            ("multigrad_resource_device_peak_bytes",
             sample["device_peak_bytes"],
             "Peak device memory (high-water), summed over local "
             "devices."),
            ("multigrad_resource_device_bytes_limit",
             sample["device_bytes_limit"],
             "Device memory capacity, summed over local devices."),
            ("multigrad_resource_busy_frac",
             sample["busy_frac"],
             "Fraction of the last sample window spent inside "
             "bucket dispatches."),
            ("multigrad_resource_busy_seconds_total",
             sample["busy_s_total"],
             "Cumulative dispatch-busy seconds."),
            ("multigrad_resource_compile_count",
             sample["compile_count"],
             "Programs built through the program cache."),
            ("multigrad_resource_compile_seconds_total",
             sample["compile_s_total"],
             "Cumulative program-build wall seconds."),
            ("multigrad_resource_compile_cache_hits",
             sample["compile_hits"], "Program-cache hits."),
            ("multigrad_resource_compile_cache_misses",
             sample["compile_misses"], "Program-cache misses."),
            ("multigrad_resource_uptime_seconds",
             sample["uptime_s"], "Monitor uptime (seconds)."),
        )
        try:
            for name, value, help_ in gauges:
                if value is not None:
                    lm.set(name, float(value), help=help_)
        except Exception:
            pass

    def _note_degraded(self, reason: str):
        with self._lock:
            if self._degraded_reported:
                return
            self._degraded_reported = True
        if self.logger is not None:
            try:
                self.logger.log("resource_monitor_degraded",
                                reason=reason)
            except Exception:
                pass

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded_reported

    # -- views --------------------------------------------------------------
    def snapshot(self) -> Optional[dict]:
        """The latest sample reduced to the compact wire schema
        (:data:`SNAPSHOT_KEYS`); ``None`` before the first sample."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
        if last is None:
            return None
        snap = {k: last[k] for k in SNAPSHOT_KEYS if k in last}
        snap["t"] = last["t"]
        return snap

    def ring(self) -> list:
        """The bounded sample ring, oldest first (what postmortem
        bundles capture)."""
        with self._lock:
            return list(self._ring)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ResourceMonitor":
        """Launch the daemon sampler thread (idempotent); takes an
        immediate first sample so snapshots exist right away."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self.sample()
        self._thread = threading.Thread(
            target=self._loop, name="mgt-resource-monitor", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.sample()

    def close(self):
        """Stop the sampler and take one final sample (the ring's
        last entry is the process's last known state)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self.sample()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


def autoscaler_inputs(live, monitor: Optional[ResourceMonitor] = None,
                      hop: str = "queue_wait", rollup=None,
                      window_s: float = 300.0) -> dict:
    """The documented autoscaler input contract, in one place (v2:
    windowed + trend-aware).

    ``{"busy_frac", "queue_wait_p95_s", "headroom_bytes",
    "queue_wait_p95_trend", "busy_frac_sustained",
    "slo_burn_rate"}``, each ``None`` when unmeasured:

    * ``busy_frac`` — the monitor's latest window duty cycle (scale
      OUT on sustained high values, IN on sustained idle);
    * ``queue_wait_p95_s`` — **windowed** p95 of the queue-wait
      latency over the trailing ``window_s``, from the rollup
      store's per-window samples.  Falls back to the cumulative
      ``queue_wait`` hop histogram when no history plane exists —
      the pre-PR-20 value, which can never *fall* once a burst has
      inflated it;
    * ``headroom_bytes`` — device capacity minus MEASURED peak (how
      much bigger a bucket the worker could take; feeds bucket
      sizing, and a near-zero value vetoes scale-in consolidation);
    * ``queue_wait_p95_trend`` — least-squares slope (s/s) of the
      windowed queue wait: the ROADMAP's "queue_wait p95 *rising* →
      scale out" signal, positive while latency climbs;
    * ``busy_frac_sustained`` — windowed mean duty cycle: the
      "*sustained* idle → scale in" signal one instantaneous sample
      cannot provide;
    * ``slo_burn_rate`` — the worst per-class error-budget burn rate
      (``multigrad_slo_budget_burn_rate`` gauges): above ~1.0 the
      fleet is eating budget faster than sustainable, the strongest
      scale-out signal of the three.

    ``live`` is a :class:`~multigrad_tpu.telemetry.LiveMetrics` (or
    anything with a ``metrics`` attribute); values fall back to the
    exported gauges when no ``monitor`` is passed.  ``rollup`` is a
    :class:`~multigrad_tpu.telemetry.RollupStore`; without one the
    windowed fields read the ``multigrad_rollup_*`` gauges an
    attached store exports (:meth:`~multigrad_tpu.telemetry.rollup
    .RollupStore.export`), so a scheduler built with ``history=True``
    feeds v2 through the registry with no extra plumbing.
    """
    lm = getattr(live, "metrics", live)
    busy = headroom = None
    snap = monitor.snapshot() if monitor is not None else None
    if snap is not None:
        busy = snap.get("busy_frac")
        limit, peak = snap.get("device_bytes_limit"), \
            snap.get("device_peak_bytes")
        if limit is not None and peak is not None:
            headroom = int(limit - peak)
    elif lm is not None:
        busy = lm.value("multigrad_resource_busy_frac")
        limit = lm.value("multigrad_resource_device_bytes_limit")
        peak = lm.value("multigrad_resource_device_peak_bytes")
        if limit is not None and peak is not None:
            headroom = int(limit - peak)
    p95 = trend = sustained = None
    if rollup is not None:
        from .rollup import BUSY_FRAC, QUEUE_WAIT_S
        p95 = rollup.quantile_over(QUEUE_WAIT_S, 0.95, window_s)
        trend = rollup.trend(QUEUE_WAIT_S, window_s)
        sustained = rollup.mean_over(BUSY_FRAC, window_s)
    elif lm is not None:
        p95 = lm.value("multigrad_rollup_queue_wait_p95_s")
        trend = lm.value("multigrad_rollup_queue_wait_trend")
        sustained = lm.value(
            "multigrad_rollup_busy_frac_sustained")
    if p95 is None and lm is not None:
        # Cumulative-histogram fallback: the v1 estimator, kept so a
        # history-less process still reports *something* — with the
        # documented caveat that it cannot see a trend.
        for name in ("multigrad_serve_hop_seconds",
                     "multigrad_fleet_hop_seconds"):
            for labels in lm.label_sets(name):
                if labels.get("hop") == hop:
                    p95 = lm.quantile(name, 0.95, labels=labels)
                    break
            if p95 is not None:
                break
    burn = None
    if lm is not None:
        for labels in lm.label_sets(
                "multigrad_slo_budget_burn_rate"):
            v = lm.value("multigrad_slo_budget_burn_rate",
                         labels=labels)
            if v is not None and (burn is None or v > burn):
                burn = v
    return {"busy_frac": busy, "queue_wait_p95_s": p95,
            "headroom_bytes": headroom,
            "queue_wait_p95_trend": trend,
            "busy_frac_sustained": sustained,
            "slo_burn_rate": burn}
