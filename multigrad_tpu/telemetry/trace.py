"""Trace waterfalls from per-process trace JSONLs.

::

    python -m multigrad_tpu.telemetry.trace router.trace.jsonl w*.trace.jsonl
    python -m multigrad_tpu.telemetry.trace ... --slowest 3
    python -m multigrad_tpu.telemetry.trace ... --trace 1f3c2a
    python -m multigrad_tpu.telemetry.trace ... --json

Merges the ``trace_span`` records the fleet router, the workers'
schedulers, and single-process :class:`~multigrad_tpu.serve
.scheduler.FitScheduler`\\ s wrote (one JSONL per process, see
:mod:`.tracing`) by ``trace_id`` and renders each request's journey
as a parent-linked waterfall: every hop (``route`` → ``rpc_send`` →
``queue_wait`` → ``bucket_coalesce`` → ``dispatch`` →
``adam_segments`` → ``finalize`` → ``result_return``), one explicit
``requeue`` hop per worker generation a chaos-killed request
migrated across, span offsets and durations against the root
``request`` span, and a **coverage** figure — the fraction of the
request's end-to-end latency accounted for by the union of its
child spans (union, not sum: overlapping spans like ``queue_wait``
⊇ ``bucket_coalesce`` are counted once).

Per-trace **completeness** is checked structurally: exactly one
root span and every ``parent_span_id`` resolving within the trace —
the invariant the chaos suite asserts for SIGKILL'd requests.
``trace_rtt`` records (the router's heartbeat-RPC round-trip
samples) annotate the output as the wall-clock noise floor.

This module is pure stdlib and self-contained.  NB: the ``-m``
invocation still imports ``multigrad_tpu`` (and therefore jax) on
the way in — on a triage box without jax, run the file directly::

    python path/to/multigrad_tpu/telemetry/trace.py *.trace.jsonl
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence

__all__ = ["load_records", "load_spans", "group_traces",
           "trace_summary", "span_coverage", "render_waterfall",
           "render_summary_line", "main"]

TRACE_EVENT = "trace_span"      # kept in sync with .tracing


def load_records(paths: Sequence[str]) -> list:
    """Read JSONL files, skipping blank/unparseable lines (a
    SIGKILL'd worker leaves at most one torn tail line)."""
    records = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    return records


def load_spans(paths: Sequence[str]) -> list:
    """The ``trace_span`` records of a set of per-process files."""
    return [r for r in load_records(paths)
            if r.get("event") == TRACE_EVENT
            and r.get("trace_id") and r.get("span_id")]


def group_traces(spans: list) -> Dict[str, list]:
    """Merge spans by ``trace_id``; each trace's spans sorted by
    start time (root-first on ties, so waterfalls render stably)."""
    traces: Dict[str, list] = {}
    for span in spans:
        traces.setdefault(span["trace_id"], []).append(span)
    for spans_ in traces.values():
        spans_.sort(key=lambda s: (s.get("t_start") or 0.0,
                                   s.get("parent_span_id") is not None,
                                   s.get("t_end") or 0.0))
    return traces


def _interval_union(intervals: List[tuple]) -> float:
    """Total length of the union of [start, end) intervals."""
    total = 0.0
    cur_start = cur_end = None
    for start, end in sorted(intervals):
        if end <= start:
            continue
        if cur_end is None or start > cur_end:
            if cur_end is not None:
                total += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    if cur_end is not None:
        total += cur_end - cur_start
    return total


def _root_of(spans: list) -> Optional[dict]:
    roots = [s for s in spans if s.get("parent_span_id") is None]
    return roots[0] if len(roots) == 1 else None


def span_coverage(spans: list) -> Optional[float]:
    """Fraction of the root span's window covered by the union of
    its descendant spans (clipped to the root window).  ``None``
    without a root or with a zero-length root."""
    root = _root_of(spans)
    if root is None:
        return None
    r0, r1 = root.get("t_start"), root.get("t_end")
    if r0 is None or r1 is None or r1 <= r0:
        return None
    intervals = []
    for s in spans:
        if s is root:
            continue
        t0, t1 = s.get("t_start"), s.get("t_end")
        if t0 is None or t1 is None:
            continue
        t0, t1 = max(t0, r0), min(t1, r1)
        if t1 > t0:
            intervals.append((t0, t1))
    return _interval_union(intervals) / (r1 - r0)


def trace_summary(trace_id: str, spans: list) -> dict:
    """Structural summary of one merged trace: root/elapsed, span
    and hop accounting, requeue hops, services touched, and the
    completeness verdict (exactly one root, every parent id
    resolving within the trace, no zero-span trace)."""
    ids = {s["span_id"] for s in spans}
    orphans = [s["span_id"] for s in spans
               if s.get("parent_span_id") is not None
               and s["parent_span_id"] not in ids]
    root = _root_of(spans)
    n_roots = sum(1 for s in spans
                  if s.get("parent_span_id") is None)
    requeues = [s for s in spans if s.get("name") == "requeue"]
    hops: Dict[str, float] = {}
    for s in spans:
        if s is root:
            continue
        name = s.get("name", "?")
        hops[name] = hops.get(name, 0.0) + (s.get("elapsed_s") or 0.0)
    # Job-DAG traces (serve.jobs): roll hops up per pipeline stage —
    # each ``stage`` span's subtree is one stage attempt.
    stages: Dict[str, dict] = {}
    for s in spans:
        if s.get("name") != "stage" or not s.get("stage"):
            continue
        entry = stages.setdefault(
            s["stage"], {"elapsed_s": 0.0, "attempts": 0, "ok": True})
        entry["elapsed_s"] += s.get("elapsed_s") or 0.0
        entry["attempts"] += 1
        entry["ok"] = entry["ok"] and bool(s.get("ok", True))
    return {
        "trace_id": trace_id,
        "n_spans": len(spans),
        "root": root,
        "elapsed_s": (root["t_end"] - root["t_start"])
        if root else None,
        "outcome": (root or {}).get("outcome"),
        "complete": bool(spans) and n_roots == 1 and not orphans,
        "orphans": orphans,
        "n_roots": n_roots,
        "coverage": span_coverage(spans),
        "hops": hops,
        "stages": stages,
        "requeues": [{"from": s.get("from_worker"),
                      "to": s.get("to_worker"),
                      "reason": s.get("reason"),
                      "bundle": s.get("bundle")} for s in requeues],
        "services": sorted({s.get("service") for s in spans
                            if s.get("service")}),
        "bundles": sorted({s.get("bundle") for s in spans
                           if s.get("bundle")}),
    }


def _fmt_s(v, nd=3):
    return "-" if v is None else f"{v:.{nd}f}s"


def _span_label(span: dict) -> str:
    """One human-readable token per hop; the requeue label names
    both worker generations (``requeue w0->w1``) — the line the
    chaos CI greps for."""
    name = span.get("name", "?")
    if name == "requeue":
        to = span.get("to_worker") or "lost"
        return f"requeue {span.get('from_worker', '?')}->{to}"
    parts = [name]
    # Job-DAG spans carry their pipeline position in the label, so a
    # multi-stage waterfall reads scan/ensemble/… at a glance.
    if name == "job" and span.get("job_id"):
        parts.append(str(span["job_id"]))
    if name == "stage" and span.get("stage"):
        parts.append(str(span["stage"]))
        if (span.get("attempt") or 1) > 1:
            parts.append(f"attempt={span['attempt']}")
    if name == "request" and span.get("stage"):
        parts.append(f"[{span['stage']}]")
    if span.get("worker"):
        parts.append(str(span["worker"]))
    if name == "dispatch":
        if span.get("bucket") is not None:
            parts.append(f"K={span['bucket']}")
        if span.get("compiled") is not None:
            parts.append("compiled" if span["compiled"] else "cached")
    if name == "rpc_send" and (span.get("attempts") or 1) > 1:
        parts.append(f"attempts={span['attempts']}")
    if not span.get("ok", True):
        parts.append("FAILED")
    return " ".join(parts)


def render_summary_line(summary: dict) -> str:
    cov = summary.get("coverage")
    parts = [f"trace {summary['trace_id'][:12]}",
             _fmt_s(summary["elapsed_s"]),
             f"{summary['n_spans']} spans",
             "coverage " + (f"{cov:.0%}" if cov is not None
                            else "-")]
    if summary.get("outcome"):
        parts.append(f"outcome={summary['outcome']}")
    if summary.get("stages"):
        parts.append(f"{len(summary['stages'])} stage(s)")
    if summary["requeues"]:
        parts.append(f"{len(summary['requeues'])} requeue(s)")
    parts.append("complete" if summary["complete"]
                 else "INCOMPLETE")
    return "  ".join(parts)


def render_waterfall(trace_id: str, spans: list,
                     width: int = 30) -> str:
    """One trace as an indented, bar-charted waterfall."""
    summary = trace_summary(trace_id, spans)
    lines = [render_summary_line(summary)]
    root = summary["root"]
    if root is None:
        lines.append("  (no single root span — cannot anchor the "
                     "waterfall; spans listed flat)")
        r0, dur = None, None
    else:
        r0 = root["t_start"]
        dur = max(root["t_end"] - r0, 1e-9)

    by_parent: Dict[Optional[str], list] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent_span_id"), []).append(s)

    def depth_of(span, seen=()):
        parent = span.get("parent_span_id")
        if parent is None or span["span_id"] in seen:
            return 0
        parents = [s for s in spans if s["span_id"] == parent]
        if not parents:
            return 1
        return 1 + depth_of(parents[0],
                            seen + (span["span_id"],))

    def emit(span):
        t0, t1 = span.get("t_start"), span.get("t_end")
        elapsed = span.get("elapsed_s") or 0.0
        if r0 is not None and t0 is not None and t1 is not None:
            off = max(0, min(width - 1,
                             int((t0 - r0) / dur * width)))
            end = max(off + 1, min(width,
                                   int(round((t1 - r0) / dur
                                             * width))))
            bar = " " * off + "#" * (end - off) \
                + " " * (width - end)
            rel = f"+{t0 - r0:8.3f}s"
        else:
            bar = "?" * width
            rel = "        ?"
        indent = "  " * depth_of(span)
        label = indent + _span_label(span)
        svc = span.get("service")
        lines.append(f"  {rel} {_fmt_s(elapsed):>10}  |{bar}|  "
                     f"{label}"
                     + (f"  @{svc}" if svc else ""))

    # Pre-order walk: each span's children (by start time) directly
    # under it; orphans appended at the end so nothing is hidden.
    emitted = set()

    def walk(span):
        if span["span_id"] in emitted:
            return
        emitted.add(span["span_id"])
        emit(span)
        for child in sorted(by_parent.get(span["span_id"], []),
                            key=lambda s: s.get("t_start") or 0.0):
            walk(child)

    for span in sorted(by_parent.get(None, []),
                       key=lambda s: s.get("t_start") or 0.0):
        walk(span)
    for span in spans:
        if span["span_id"] not in emitted:
            walk(span)
    return "\n".join(lines)


def _rtt_floor(records: list) -> Optional[dict]:
    rtts = sorted(r.get("rtt_s") for r in records
                  if r.get("event") == "trace_rtt"
                  and isinstance(r.get("rtt_s"), (int, float)))
    if not rtts:
        return None
    return {"n": len(rtts),
            "median_s": rtts[len(rtts) // 2],
            "max_s": rtts[-1]}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.trace",
        description="Merge per-process trace JSONLs by trace_id and "
                    "render per-request waterfalls.")
    parser.add_argument("paths", nargs="+",
                        help="trace .jsonl files (router + workers)")
    parser.add_argument("--slowest", type=int, default=1,
                        metavar="N",
                        help="render full waterfalls for the N "
                             "slowest traces (default 1; 0 = "
                             "summary lines only)")
    parser.add_argument("--trace", default=None, metavar="ID",
                        help="render one trace (id prefix match)")
    parser.add_argument("--json", action="store_true",
                        help="emit merged traces + summaries as JSON")
    args = parser.parse_args(argv)

    try:
        records = load_records(args.paths)
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 1
    spans = [r for r in records if r.get("event") == TRACE_EVENT
             and r.get("trace_id") and r.get("span_id")]
    traces = group_traces(spans)
    if not traces:
        print("no trace_span records found", file=sys.stderr)
        return 1
    summaries = sorted(
        (trace_summary(tid, tspans)
         for tid, tspans in traces.items()),
        key=lambda s: -(s["elapsed_s"] or 0.0))
    rtt = _rtt_floor(records)

    if args.trace is not None:
        matches = [tid for tid in traces
                   if tid.startswith(args.trace)]
        if len(matches) != 1:
            print(f"--trace {args.trace!r} matches {len(matches)} "
                  f"traces (need exactly 1)", file=sys.stderr)
            return 1
        print(render_waterfall(matches[0], traces[matches[0]]))
        return 0

    if args.json:
        print(json.dumps({
            "files": list(args.paths),
            "n_traces": len(traces),
            "rpc_rtt": rtt,
            "traces": [{**s, "root": None,
                        "spans": traces[s["trace_id"]]}
                       for s in summaries],
        }, indent=1, default=str))
        return 0

    incomplete = [s for s in summaries if not s["complete"]]
    requeued = [s for s in summaries if s["requeues"]]
    print(f"{len(traces)} traces over {len(args.paths)} file(s): "
          f"{len(requeued)} with requeue hops, "
          f"{len(incomplete)} incomplete"
          + (f"; rpc rtt median {rtt['median_s'] * 1e3:.2f}ms "
             f"(n={rtt['n']})" if rtt else ""))
    for s in summaries:
        print(render_summary_line(s))
    for s in summaries[:max(0, args.slowest)]:
        print()
        print(render_waterfall(s["trace_id"],
                               traces[s["trace_id"]]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
