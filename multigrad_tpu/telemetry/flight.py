"""Flight recorder: bounded record ring + anomaly postmortems.

A long fit that dies — NaN loss, diverging sampler, wedged prefetch
thread — is only debuggable if *what happened just before* survives
the crash.  The :class:`FlightRecorder` is a telemetry **sink** (give
it to :class:`~multigrad_tpu.telemetry.MetricsLogger` next to the
JSONL file): every record the fit emits — ``adam`` taps, ``comm``
accounting, ``span``\\ s, ``heartbeat``\\ s — lands in a bounded
in-memory ring, and on an anomaly the recorder dumps a
**self-contained postmortem bundle** (one JSON file: the ring
contents, the run record, program-cache keys, jaxpr digests, the
last checkpoint path, the trip reason) and the fit entry points
raise :class:`FlightRecorderTripped` with the bundle path (also
stamped into the ``fit_summary`` record).

Three trigger classes:

* **non-finite sentinel** — an in-graph watch
  (:class:`NonFiniteSentinel`) compiled into the Adam segment scan
  and the HMC sampling scan: a ``lax.cond``-gated
  ``jax.debug.callback`` that fires the first time loss/|grad| (or
  the sampler's potential) goes NaN/Inf.  Static like the telemetry
  taps — the sentinel joins the program cache key, so arming it
  costs one build and zero retraces afterwards.  Fatal: the fit
  raises.
* **heartbeat stall** — the recorder sees the ``stall`` records the
  :class:`~multigrad_tpu.telemetry.Heartbeat` thread writes and
  dumps a bundle (non-fatal by default: a transient stall should
  not kill a fit that recovers; set ``fatal_on_stall=True`` for
  fail-fast fleets).
* **divergence spike** — a jump of ``divergence_spike`` or more in
  the cumulative divergence count between consecutive ``hmc`` tap
  records dumps a bundle (non-fatal: the run's statistics decide).

Wiring::

    recorder = FlightRecorder(dump_dir="postmortems")
    log = MetricsLogger(JsonlSink("run.jsonl"), recorder)
    model.run_adam(guess, nsteps, telemetry=log, log_every=10,
                   flight=recorder)     # raises on NaN, bundle saved

This module imports only stdlib/numpy at module level (jax lazily
inside the traced/host paths), per the telemetry package contract.
"""
from __future__ import annotations

import collections
import hashlib
import json
import os
import tempfile
import threading
import time
from functools import partial
from typing import Optional

import numpy as np

from .metrics import _jsonable

__all__ = ["FlightRecorder", "FlightRecorderTripped",
           "NonFiniteSentinel", "jaxpr_digest"]


def _strict_json(value):
    """Replace non-finite floats with their string names.

    Postmortem bundles embed NaN/Inf by construction (the trip's
    whole point); ``json.dump``'s default would write bare ``NaN``
    tokens — valid for Python's lenient reader, rejected by every
    strict RFC-8259 parser (jq, JSON.parse, fleet dashboards).  A
    fleet-readable artifact gets ``"NaN"``/``"Infinity"`` strings
    instead.
    """
    if isinstance(value, float):
        if value != value:
            return "NaN"
        if value == float("inf"):
            return "Infinity"
        if value == float("-inf"):
            return "-Infinity"
        return value
    if isinstance(value, dict):
        return {k: _strict_json(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_strict_json(v) for v in value]
    return value


class FlightRecorderTripped(RuntimeError):
    """A fatal flight-recorder trip (non-finite loss/grad/potential).

    ``bundle_path`` points at the postmortem JSON; ``reason`` and
    ``step`` carry the trigger.
    """

    def __init__(self, reason: str, bundle_path: Optional[str],
                 step=None):
        self.reason = reason
        self.bundle_path = bundle_path
        self.step = step
        at = f" at step {step}" if step is not None else ""
        super().__init__(
            f"flight recorder tripped ({reason}{at}); postmortem "
            f"bundle: {bundle_path}")


def jaxpr_digest(fn, *args) -> Optional[str]:
    """Short stable digest of ``fn``'s abstract trace (best effort).

    One zero-FLOP ``jax.make_jaxpr`` trace over abstracted ``args``
    → sha256 of the printed jaxpr, 16 hex chars.  Returns ``None``
    on any failure — a postmortem must never crash on its own
    context gathering.
    """
    try:
        import jax

        from ..analysis.jaxprs import abstractify
        args = jax.tree_util.tree_map(abstractify, args)
        closed = jax.make_jaxpr(fn)(*args)
        return hashlib.sha256(str(closed).encode()).hexdigest()[:16]
    except Exception:
        return None


class NonFiniteSentinel:
    """In-graph non-finite watch bound to a :class:`FlightRecorder`.

    Traced like a :class:`~multigrad_tpu.telemetry.ScalarTap`: the
    check is pure device arithmetic, the emit is a ``lax.cond``-gated
    unordered ``jax.debug.callback``, and the sentinel hashes by
    ``(recorder identity, name)`` so it can join a program cache key
    without ever forcing a retrace for the same recorder.  Obtain
    instances via :meth:`FlightRecorder.sentinel` (which caches one
    per name — a fresh object per fit would defeat the cache key).
    """

    def __init__(self, recorder: "FlightRecorder", name: str):
        self.recorder = recorder
        self.name = name

    def _key(self):
        return (id(self.recorder), self.name)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return (isinstance(other, NonFiniteSentinel)
                and self._key() == other._key())

    def _callback(self, names, step, *values):
        host = {}
        for n, v in zip(names, values):
            arr = np.asarray(v)
            host[n] = float(arr) if arr.ndim == 0 \
                else [float(x) for x in arr.ravel()]
        self.recorder._on_nonfinite(self.name,
                                    int(np.asarray(step)), host)

    def watch(self, step, values: dict, gate=None):
        """Traced: trip iff any entry of ``values`` is non-finite.

        Call from inside jit/scan/shard_map; ``gate`` is an optional
        extra traced-bool predicate (e.g. ``axis_index == 0`` inside
        shard_map so one shard speaks for replicated values, or a
        not-yet-fired latch carried through the scan).  Returns the
        raw non-finite flag (gate NOT applied) so scan callers can
        latch it: once a fit goes NaN every later step stays NaN,
        and without a latch each one would pay a host callback.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = jnp.asarray(step)
        bad = jnp.zeros((), bool)
        vals = tuple(jnp.asarray(v) for v in values.values())
        for v in vals:
            bad = jnp.logical_or(bad, ~jnp.all(jnp.isfinite(v)))
        fire = bad if gate is None else jnp.logical_and(bad, gate)
        cb = partial(self._callback, tuple(values))

        def _emit(args):
            jax.debug.callback(cb, *args)
            return ()

        def _skip(args):
            return ()

        lax.cond(fire, _emit, _skip, (step,) + vals)
        return bad


class FlightRecorder:
    """Bounded record ring + postmortem dumper (a telemetry sink).

    Parameters
    ----------
    dump_dir : str, optional
        Where bundles land (created on first dump).  Default: a
        fresh ``mkdtemp`` child — bundles are never silently
        clobbered between runs.
    capacity : int
        Ring size — the "last K records" a bundle preserves.
    trip_on_stall : bool
        Dump a bundle when a ``stall`` record flows through
        (non-fatal unless ``fatal_on_stall``).
    fatal_on_stall : bool
        Treat heartbeat stalls as fatal (the fit raises once it
        regains the host loop).
    divergence_spike : int, optional
        Dump when the cumulative divergence count in consecutive
        ``hmc`` records jumps by at least this much (None disables).
    context : dict, optional
        Extra provenance baked into every bundle (job id, config
        path, ...); extend later with :meth:`attach`.

    One recorder serves one fit at a time; call :meth:`reset`
    between fits to re-arm (the drivers do not reset automatically —
    a tripped recorder keeps refusing until the operator looks).
    """

    def __init__(self, dump_dir: Optional[str] = None,
                 capacity: int = 512, trip_on_stall: bool = True,
                 fatal_on_stall: bool = False,
                 divergence_spike: Optional[int] = 50,
                 context: Optional[dict] = None):
        self.dump_dir = dump_dir
        self.capacity = int(capacity)
        self.trip_on_stall = bool(trip_on_stall)
        self.fatal_on_stall = bool(fatal_on_stall)
        self.divergence_spike = divergence_spike
        from .._lockdep import make_rlock
        self._ring = collections.deque(maxlen=self.capacity)
        # Re-entrant: write() -> trip() -> dump() all touch recorder
        # state; dump snapshots under the lock and does its file IO
        # outside it.
        self._lock = make_rlock(
            "telemetry.flight.FlightRecorder._lock")
        self._context = dict(context or {})
        self._watched: dict = {}
        self._run_record: Optional[dict] = None
        self._sentinels: dict = {}
        self._last_divergences: Optional[float] = None
        self._seq = 0
        self.reason: Optional[str] = None
        self.fatal_step = None
        self.bundle_path: Optional[str] = None
        self._fatal = False

    # -- sink protocol ------------------------------------------------------
    def write(self, record: dict):
        with self._lock:
            self._ring.append(dict(record))
            event = record.get("event")
            if event == "run":
                self._run_record = dict(record)
            elif event == "stall" and self.trip_on_stall:
                self.trip("heartbeat_stall",
                          fatal=self.fatal_on_stall,
                          stalled_s=record.get("stalled_s"),
                          step=record.get("step"))
            elif event == "hmc" and self.divergence_spike:
                div = record.get("divergences")
                if isinstance(div, (list, tuple)):
                    div = sum(div)
                if isinstance(div, (int, float)):
                    prev = self._last_divergences
                    if (prev is not None
                            and div - prev >= self.divergence_spike):
                        self.trip("divergence_spike", fatal=False,
                                  divergences=div, previous=prev,
                                  step=record.get("step"))
                    self._last_divergences = div

    def close(self):
        pass

    # -- fit-driver context -------------------------------------------------
    def attach(self, **context):
        """Merge provenance into future bundles (checkpoint path,
        config digest, ...).  The fit drivers call this; users can
        too."""
        with self._lock:
            self._context.update(context)

    def watch_program(self, label: str, program, args):
        """Register a program for jaxpr-digest capture at dump time.

        ``args`` are example (concrete or abstract) arguments —
        abstracted to ``ShapeDtypeStruct``\\ s immediately, so the
        recorder never pins (possibly donated or multi-GB) buffers;
        the digest trace itself runs only when a bundle is actually
        dumped, so arming costs nothing on the happy path.
        """
        try:
            import jax

            from ..analysis.jaxprs import abstractify
            args = jax.tree_util.tree_map(abstractify, args)
        except Exception:
            return                # context gathering must never raise
        with self._lock:
            self._watched[label] = (program, args)

    def sentinel(self, name: str = "fit") -> NonFiniteSentinel:
        """The per-name cached in-graph watch (stable identity, so
        programs keyed on it never retrace for the same recorder)."""
        with self._lock:
            if name not in self._sentinels:
                self._sentinels[name] = NonFiniteSentinel(self, name)
            return self._sentinels[name]

    # -- trip + dump --------------------------------------------------------
    @property
    def tripped(self) -> bool:
        return self.reason is not None

    @property
    def fatal(self) -> bool:
        return self._fatal

    def _on_nonfinite(self, name: str, step: int, values: dict):
        self.trip(f"non_finite_{name}", fatal=True, step=step,
                  values=values)

    def trip(self, reason: str, fatal: bool = True, step=None,
             **detail) -> Optional[str]:
        """Record an anomaly and dump a bundle.  Returns the bundle
        path.

        The first trip dumps; repeated trips at the same severity are
        no-ops (a NaN scan fires its sentinel once per remaining
        step — one bundle tells the story).  A FATAL trip after only
        non-fatal ones ESCALATES: it dumps a fresh bundle (the ring
        now holds the records around the actual failure, not the
        earlier stall) and takes over ``reason``/``bundle_path``, so
        :class:`FlightRecorderTripped` always names the trip that
        killed the fit.
        """
        with self._lock:
            first = self.reason is None
            escalating = fatal and not self._fatal
            if fatal:
                self._fatal = True
                if self.fatal_step is None:
                    self.fatal_step = step
            if first or escalating:
                self.reason = reason
                path = self.dump(reason, step=step, **detail)
                if path is not None:
                    self.bundle_path = path
            return self.bundle_path

    def dump(self, reason: str = "manual", step=None,
             **detail) -> Optional[str]:
        """Write a self-contained postmortem bundle; returns its path.

        The bundle is one JSON file: trip metadata, the run record,
        attached context (last checkpoint path, cache keys, ...),
        jaxpr digests of watched programs, and the ring contents.
        Any failure is swallowed into a ``None`` return — the dump
        path must never add a second failure to the one being
        reported.
        """
        try:
            with self._lock:
                if self.dump_dir is None:
                    self.dump_dir = tempfile.mkdtemp(
                        prefix="mgt_postmortem_")
                os.makedirs(self.dump_dir, exist_ok=True)
                self._seq += 1
                seq = self._seq
                ring = list(self._ring)
                context = dict(self._context)
                run_record = self._run_record
                watched = dict(self._watched)
            try:
                import jax
                process = jax.process_index()
            except Exception:
                process = 0
            digests = {label: jaxpr_digest(program, *args)
                       for label, (program, args) in watched.items()}
            bundle = {
                "event": "postmortem",
                "t": time.time(),
                "reason": reason,
                "step": step,
                "detail": _jsonable(detail),
                "process_index": process,
                "run": _jsonable(run_record),
                "context": _jsonable(context),
                "jaxpr_digests": digests,
                "ring_records": len(ring),
                "ring": _jsonable(ring),
            }
            path = os.path.join(
                self.dump_dir,
                f"postmortem_p{process}_{seq:03d}_{reason}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(_strict_json(bundle), f, indent=1,
                          allow_nan=False)
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    def reset(self):
        """Re-arm for the next fit (ring and context survive; trip
        state clears)."""
        with self._lock:
            self.reason = None
            self.fatal_step = None
            self.bundle_path = None
            self._fatal = False
            self._last_divergences = None

    def raise_if_fatal(self):
        """Raise :class:`FlightRecorderTripped` if a fatal trip
        occurred (the fit drivers' post-run check)."""
        if self._fatal:
            raise FlightRecorderTripped(self.reason or "fatal",
                                        self.bundle_path,
                                        step=self.fatal_step)
