"""Collective-traffic accounting: count calls and bytes per reduction.

The paper's value proposition is a *communication* bound —
O(|sumstats| + |params|) per loss-and-grad evaluation, independent of
data size — and this module turns that claim from an assertion into a
measurement.  Every collective in :mod:`multigrad_tpu.parallel`
(``psum``/``all_gather``/``reduce_sum``, plus the implicit transpose
all-reduce of the vma-era gradient path, which ``core/model.py``
records explicitly) reports its payload to any active
:class:`CommCounter` **at trace time**: the payloads are static
shapes, so tracing a program once under a counter yields the exact
per-execution traffic without ever running it.

Usage::

    with CommCounter() as cc:
        jax.eval_shape(program, *abstract_args)   # traces, runs nothing
    cc.total_bytes        # payload bytes per program execution
    cc.calls              # {"psum": 2, ...}

or, one level up, :func:`measure_model_comm` traces a fresh build of a
model's SPMD entry point and returns the counter — the number the
acceptance test compares against the hand-computed ``|y| + |params|``.

Counting convention: one "call" per collective primitive bound during
the trace, with ``bytes`` the *logical payload* (element count ×
itemsize of the reduced array, summed over pytree leaves).  Wire
traffic for a concrete interconnect is a topology-dependent multiple
of this (e.g. ring all-reduce moves ``2·(N-1)/N`` × payload per
device); the payload is the invariant the O(|y|+|params|) claim is
about.  Collectives vmapped inside the block (e.g. the per-row VJPs
of ``sumstats_jac_rev``, or per-chain batched kernels) count once per
logical call with the batched payload — exactly the traffic the
batch executes.

This module imports only jax/numpy (never :mod:`..parallel` or
:mod:`..core`) so the collectives layer can depend on it cycle-free;
the model-level helpers import lazily inside the function body.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

__all__ = ["CommCounter", "record_collective", "traced_comm",
           "measure_model_comm", "leaf_nbytes"]

_ACTIVE = threading.local()


def _active_counters() -> list:
    stack = getattr(_ACTIVE, "stack", None)
    if stack is None:
        stack = _ACTIVE.stack = []
    return stack


def leaf_nbytes(leaf) -> int:
    """Payload bytes of one array-like/tracer/aval/ShapeDtypeStruct leaf.

    THE byte-accounting rule, shared between the runtime
    :class:`CommCounter` and the static shard-safety analyzer
    (:mod:`multigrad_tpu.analysis`): both weigh payloads with this
    function, so trace-time measurement and jaxpr-level verification
    can never disagree on what a collective moves.

    A ``vmap`` batching tracer exposes the UNBATCHED shape — but the
    executed collective moves the batched payload (one per vmapped
    instance, e.g. per HMC chain or per Jacobian row), so unwrap to
    the underlying batched value before reading the shape.  Nested
    vmaps unwrap recursively.
    """
    try:
        from jax.interpreters.batching import BatchTracer
    except ImportError:          # pragma: no cover - jax relayout
        BatchTracer = ()
    if isinstance(leaf, BatchTracer):
        return leaf_nbytes(leaf.val)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        # Python scalar contribution: weak-typed float/int payload.
        return np.dtype(np.result_type(type(leaf))).itemsize
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # Extension dtypes (e.g. PRNG keys) expose an itemsize via
        # their key data; fall back to 4 bytes per element.
        itemsize = getattr(dtype, "itemsize", 4)
    return int(np.prod(shape, dtype=np.int64)) * int(itemsize)


class CommCounter:
    """Context manager accumulating collective calls/bytes per op.

    Attributes
    ----------
    calls : dict[str, int]
        Number of collective primitives bound, per op name.
    bytes : dict[str, int]
        Logical payload bytes, per op name.
    """

    def __init__(self):
        self.calls: dict = {}
        self.bytes: dict = {}

    # -- accounting ---------------------------------------------------------
    def record(self, op: str, nbytes: int, n_calls: int = 1):
        self.calls[op] = self.calls.get(op, 0) + n_calls
        self.bytes[op] = self.bytes.get(op, 0) + nbytes

    def merge(self, other: "CommCounter") -> "CommCounter":
        for op, n in other.calls.items():
            self.record(op, other.bytes.get(op, 0), n)
        return self

    def scaled(self, factor: int) -> "CommCounter":
        """A new counter with every count multiplied by ``factor`` —
        e.g. per-chunk traffic × number of chunks."""
        out = CommCounter()
        for op, n in self.calls.items():
            out.record(op, self.bytes.get(op, 0) * factor, n * factor)
        return out

    @property
    def total_calls(self) -> int:
        return sum(self.calls.values())

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    def summary(self) -> dict:
        return {
            "total_bytes": int(self.total_bytes),
            "total_calls": int(self.total_calls),
            "bytes_by_op": {k: int(v) for k, v in self.bytes.items()},
            "calls_by_op": {k: int(v) for k, v in self.calls.items()},
        }

    def step_record(self, scope: Optional[str] = None, **extra) -> dict:
        """The canonical ``comm``-event payload for one program
        execution — the ONE schema every log site and the report CLI
        share (``bytes_per_step``/``calls_per_step``/``bytes_by_op``);
        hand-assembling these keys at call sites is how schemas fork.
        """
        rec: dict = {}
        if scope is not None:
            rec["scope"] = scope
        rec.update(
            bytes_per_step=int(self.total_bytes),
            calls_per_step=int(self.total_calls),
            bytes_by_op={k: int(v) for k, v in self.bytes.items()},
            calls_by_op={k: int(v) for k, v in self.calls.items()},
        )
        rec.update(extra)
        return rec

    def __repr__(self):
        return (f"CommCounter(total_bytes={self.total_bytes}, "
                f"calls={self.calls})")

    # -- context manager ----------------------------------------------------
    def __enter__(self):
        _active_counters().append(self)
        return self

    def __exit__(self, *exc):
        _active_counters().remove(self)
        return False


def record_collective(op: str, value, n_calls: int = 1):
    """Report one collective's payload to every active counter.

    Called by the instrumented collectives at trace time (tracers have
    static shapes, so the accounting is exact) and by ``core/model.py``
    for the vma-era implicit transpose all-reduce, which has no
    explicit primitive to wrap.  No-op (one attribute read) when no
    counter is active, so the instrumentation never costs the hot
    path anything measurable.
    """
    stack = getattr(_ACTIVE, "stack", None)
    if not stack:
        return
    import jax

    nbytes = sum(leaf_nbytes(leaf)
                 for leaf in jax.tree_util.tree_leaves(value))
    for counter in stack:
        counter.record(op, nbytes, n_calls)


def traced_comm(fn, *args, **kwargs) -> CommCounter:
    """Trace ``fn(*args)`` abstractly and return its collective traffic.

    ``jax.eval_shape`` runs the trace (shard_map bodies included) with
    zero FLOPs; the instrumented collectives report to the returned
    counter.  ``args`` may be concrete arrays or
    ``jax.ShapeDtypeStruct``\\ s.  NB: pass a *freshly built* program,
    not a cached one — an already-compiled program replays without
    tracing and reports nothing.
    """
    import jax

    with CommCounter() as cc:
        jax.eval_shape(fn, *args, **kwargs)
    return cc


def measure_model_comm(model, params, kind: str = "loss_and_grad",
                       randkey=None) -> CommCounter:
    """Collective traffic of ONE execution of a model's SPMD program.

    Builds a fresh (uncached) program for ``kind`` (any of
    ``OnePointModel._build_local_fn``'s kinds) and traces it under a
    :class:`CommCounter`.  For the paper's headline program
    (``"loss_and_grad"``) the result is the claim itself:
    ``total_bytes == (|sumstats| + |params|) · itemsize``, independent
    of the catalog size.  Models with ``comm=None`` trace zero
    collectives.
    """
    import jax
    import jax.numpy as jnp

    with_key = randkey is not None
    program = model._build_program(kind, with_key)
    if with_key:
        from jax import random
        key = randkey if hasattr(randkey, "dtype") \
            else random.key(int(randkey))
    else:
        key = jnp.zeros(())
    with CommCounter() as cc:
        jax.eval_shape(program, jnp.asarray(
            params, dtype=jnp.result_type(float)),
            model.aux_leaves(), key)
    return cc
