"""Telemetry subsystem: metrics taps, run records, comm accounting.

The observability layer the ROADMAP's "production-scale, fast as the
hardware allows" goal rests on — you cannot trust a perf claim you
cannot measure.  Four pieces, one record stream:

* :mod:`.metrics` — :class:`MetricsLogger` with pluggable sinks
  (:class:`JsonlSink`, :class:`CsvSink`, :class:`MemorySink`) and the
  :func:`run_record` provenance header.
* :mod:`.taps` — :class:`ScalarTap`: throttled in-graph scalar
  emission via ``jax.debug.callback`` from inside jitted
  ``lax.scan`` fits and samplers (wired into ``optim/adam`` and
  ``inference/hmc``).
* :mod:`.comm` — :class:`CommCounter`: trace-time collective-payload
  accounting behind the instrumented ``parallel`` collectives; the
  empirical check of the paper's O(|sumstats|+|params|) claim
  (:func:`measure_model_comm`).
* :mod:`.spans` — nestable wall-clock :func:`span` records plus the
  :class:`Heartbeat` liveness/stall detector for long host loops.

The flight-recorder / perf-attribution layer on top:

* :mod:`.profile` — :func:`profiled_fit`: ``jax.profiler`` capture
  scoped to a fit, parsed into per-op/per-program device-time
  buckets with the tunnel-RTT floor recorded.
* :mod:`.costmodel` — static FLOP/transcendental/byte accounting
  from an abstract trace (:func:`model_cost`), folded against
  per-backend rooflines (:func:`roofline_record`): predicted vs
  measured, as a telemetry record.
* :mod:`.flight` — :class:`FlightRecorder`: a bounded record ring
  that dumps self-contained postmortem bundles on NaN/Inf (in-graph
  sentinel), heartbeat stalls, or divergence spikes; fits raise
  :class:`FlightRecorderTripped` with the bundle path.
* :mod:`.aggregate` — cross-rank JSONL merge, span-skew and
  straggler detection (``python -m multigrad_tpu.telemetry
  .aggregate rank*.jsonl``).
* :mod:`.regress` — the noise-aware bench regression gate
  (``python -m multigrad_tpu.telemetry.regress BENCH_r05.json
  BENCH_r06.json``): tunnel-RTT-derived noise floors, null-metric
  warnings, nonzero exit on regression.

The live (online) layer:

* :mod:`.live` — :class:`LiveMetrics` (counter/gauge/histogram
  registry) + :class:`LiveSink` (record-stream adapter) +
  :class:`LiveServer` (daemon-thread ``/metrics`` Prometheus
  endpoint, ``/status`` JSON, ``/fleet`` cross-rank view); pass
  ``live=`` to any fit entry point.
* :mod:`.alerts` — declarative non-fatal alert rules
  (:class:`AlertEngine`, ``alerts=``): loss plateau, gradient
  explosion, throughput drop, divergence rate, heartbeat stall —
  each emitting ``alert`` records, optionally escalating to the
  flight recorder.
* :mod:`.dashboard` — the streaming ANSI terminal dashboard
  (``python -m multigrad_tpu.telemetry.dashboard run.jsonl
  --follow``): sparklines, steps/s, ETA, divergence rates, alerts —
  over the JSONL file the fit is already writing.

Read a stream back with ``python -m multigrad_tpu.telemetry.report
run.jsonl`` (:mod:`.report`; ``--run N``/``--list-runs`` select a
run of an appended multi-run file).

The distributed-tracing layer across the serve fleet:

* :mod:`.tracing` — :class:`TraceContext` (W3C-traceparent-style
  ``trace_id``/``span_id``/``parent_span_id``, minted per request at
  the serve submit surfaces and propagated on the wire) +
  :class:`Tracer` (per-process ``trace_span`` JSONL recorder).
* :mod:`.trace` — the waterfall renderer (``python -m multigrad_tpu
  .telemetry.trace router.trace.jsonl w*.trace.jsonl``): merge by
  ``trace_id``, per-request hop waterfalls, completeness/coverage
  verdicts, ``--slowest N`` / ``--trace <id>`` / ``--json``;
  :func:`~multigrad_tpu.telemetry.aggregate.merge_traces` is the
  programmatic merge.

The resource plane:

* :mod:`.resources` — :class:`ResourceMonitor`: per-process sampler
  (host RSS, ``device.memory_stats()`` where available, busy/idle
  duty cycle from the serve dispatch hooks, compile accounting at
  the program-cache boundary) exporting ``multigrad_resource_*``
  gauges, a bounded ring for postmortems, the
  :func:`autoscaler_inputs` contract, and the per-dispatch
  :func:`measured_vs_modeled` memory-truth record.
* :mod:`.top` — the fleet-top CLI (``python -m multigrad_tpu
  .telemetry.top --once <status-url|jsonl> ...``): per-worker
  utilization / memory / compile-seconds / queue / SLO-budget
  columns from ``/status`` endpoints or telemetry JSONL streams
  (``--tenants`` flips to per-tenant usage rows).

The history plane (windowed time, not just now/forever):

* :mod:`.rollup` — :class:`RollupStore`: bounded tiered windowed
  time-series store (10 s → 1 m → 10 m rings), fed directly, as a
  :class:`MetricsLogger` sink, and by scraping a
  :class:`LiveMetrics` registry; windowed ``rate()`` / ``delta()``
  / ``quantile_over()`` / ``trend()``, compact heartbeat deltas the
  fleet router merges into a history that survives worker death,
  and the per-tenant usage series behind ``tenant_usage`` records.
* :mod:`.budget` — :class:`SloBudget` error budgets over the
  declared SLOs (remaining fraction, SRE-style multi-window burn
  rates, exhaustion ETA, ``multigrad_slo_budget_*`` gauges with
  violation-trace exemplars) and the rising-edge
  :class:`BurnRateAlert` rule for the alert engine.

This package imports only jax/numpy/stdlib at module level — never
the rest of ``multigrad_tpu`` (the cost model reaches into
:mod:`..analysis` lazily, inside functions) — so every other layer
can depend on it without cycles.
"""
from .metrics import (CsvSink, JsonlSink, MemorySink,  # noqa: F401
                      MetricsLogger, config_digest, run_record)
from .taps import ScalarTap, batch_norm, make_tap  # noqa: F401
from .comm import (CommCounter, leaf_nbytes, measure_model_comm,  # noqa: F401
                   record_collective, traced_comm)
from .spans import Heartbeat, span  # noqa: F401
from .profile import profiled_fit, summarize_device_trace  # noqa: F401
from .costmodel import (ProgramCost, estimate_program_cost,  # noqa: F401
                        model_cost, predicted_time_s,
                        roofline_record)
from .flight import (FlightRecorder, FlightRecorderTripped,  # noqa: F401
                     NonFiniteSentinel)
from .live import (LiveMetrics, LiveServer, LiveSink,  # noqa: F401
                   wire_monitoring)
from .alerts import (AlertEngine, AlertRule, DivergenceRate,  # noqa: F401
                     GradExplosion, HeartbeatStall, LossPlateau,
                     ThroughputDrop, default_rules)
from .tracing import (TraceContext, Tracer, new_trace,  # noqa: F401
                      parse_traceparent)
from .resources import (ResourceMonitor, autoscaler_inputs,  # noqa: F401
                        measured_vs_modeled)
from .rollup import RollupStore  # noqa: F401
from .budget import BurnRateAlert, SloBudget  # noqa: F401

__all__ = [
    "MetricsLogger", "JsonlSink", "CsvSink", "MemorySink",
    "run_record", "config_digest",
    "ScalarTap", "make_tap", "batch_norm",
    "CommCounter", "record_collective", "traced_comm",
    "measure_model_comm", "leaf_nbytes",
    "span", "Heartbeat",
    "profiled_fit", "summarize_device_trace",
    "ProgramCost", "estimate_program_cost", "model_cost",
    "predicted_time_s", "roofline_record",
    "FlightRecorder", "FlightRecorderTripped", "NonFiniteSentinel",
    "LiveMetrics", "LiveSink", "LiveServer", "wire_monitoring",
    "AlertEngine", "AlertRule", "LossPlateau", "GradExplosion",
    "ThroughputDrop", "DivergenceRate", "HeartbeatStall",
    "default_rules",
    "TraceContext", "Tracer", "new_trace", "parse_traceparent",
    "ResourceMonitor", "autoscaler_inputs", "measured_vs_modeled",
    "RollupStore", "SloBudget", "BurnRateAlert",
]
