"""Telemetry subsystem: metrics taps, run records, comm accounting.

The observability layer the ROADMAP's "production-scale, fast as the
hardware allows" goal rests on — you cannot trust a perf claim you
cannot measure.  Four pieces, one record stream:

* :mod:`.metrics` — :class:`MetricsLogger` with pluggable sinks
  (:class:`JsonlSink`, :class:`CsvSink`, :class:`MemorySink`) and the
  :func:`run_record` provenance header.
* :mod:`.taps` — :class:`ScalarTap`: throttled in-graph scalar
  emission via ``jax.debug.callback`` from inside jitted
  ``lax.scan`` fits and samplers (wired into ``optim/adam`` and
  ``inference/hmc``).
* :mod:`.comm` — :class:`CommCounter`: trace-time collective-payload
  accounting behind the instrumented ``parallel`` collectives; the
  empirical check of the paper's O(|sumstats|+|params|) claim
  (:func:`measure_model_comm`).
* :mod:`.spans` — nestable wall-clock :func:`span` records plus the
  :class:`Heartbeat` liveness/stall detector for long host loops.

Read a stream back with ``python -m multigrad_tpu.telemetry.report
run.jsonl`` (:mod:`.report`).

This package imports only jax/numpy/stdlib — never the rest of
``multigrad_tpu`` at module level — so every other layer can depend
on it without cycles.
"""
from .metrics import (CsvSink, JsonlSink, MemorySink,  # noqa: F401
                      MetricsLogger, config_digest, run_record)
from .taps import ScalarTap, batch_norm, make_tap  # noqa: F401
from .comm import (CommCounter, leaf_nbytes, measure_model_comm,  # noqa: F401
                   record_collective, traced_comm)
from .spans import Heartbeat, span  # noqa: F401

__all__ = [
    "MetricsLogger", "JsonlSink", "CsvSink", "MemorySink",
    "run_record", "config_digest",
    "ScalarTap", "make_tap", "batch_norm",
    "CommCounter", "record_collective", "traced_comm",
    "measure_model_comm", "leaf_nbytes",
    "span", "Heartbeat",
]
