"""Terminal summary of a telemetry JSONL stream.

::

    python -m multigrad_tpu.telemetry.report run.jsonl [more.jsonl ...]

Renders the record stream a fit/sampler/bench run produced
(:mod:`.metrics`) as a short human-readable report: provenance, the
fit's loss evolution and steps/s, HMC acceptance/divergences, the
collective-traffic accounting (the O(|sumstats|+|params|) check), the
streaming pipeline's stall fraction, span timings, and any stall
events.

This module is pure stdlib.  NB: the ``-m`` invocation above still
executes ``multigrad_tpu/__init__`` (and therefore imports jax) on
the way in — on a triage box without jax, run the file directly
instead, it is self-contained::

    python path/to/multigrad_tpu/telemetry/report.py run.jsonl
"""
from __future__ import annotations

import argparse
import json
import sys

__all__ = ["load_records", "split_runs", "list_runs", "summarize",
           "render", "main"]


def load_records(path: str) -> list:
    """Read a JSONL record stream, skipping unparseable lines (a
    truncated tail from a crashed run must not kill the report)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
    return records


def _first(v):
    """Scalar view of a tap value (batched fits emit lists)."""
    if isinstance(v, list):
        return v[0] if v else None
    return v


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def split_runs(records: list) -> list:
    """Split a stream at its ``run`` headers into per-run record
    lists.  Records before the first header (a headerless legacy
    stream) form their own leading run."""
    runs: list = []
    current: list = []
    for rec in records:
        if rec.get("event") == "run" and current:
            runs.append(current)
            current = []
        current.append(rec)
    if current:
        runs.append(current)
    return runs


def list_runs(records: list) -> list:
    """One summary row per run in a (possibly appended) stream —
    index, start time, record/event counts, final loss — so appended
    runs stay discoverable (the ``--list-runs`` CLI view)."""
    rows = []
    for i, run in enumerate(split_runs(records)):
        events: dict = {}
        final_loss = steps = None
        for rec in run:
            events[rec.get("event", "?")] = \
                events.get(rec.get("event", "?"), 0) + 1
            if rec.get("event") == "adam":
                final_loss = _first(rec.get("loss"))
                steps = rec.get("step")
            elif rec.get("event") == "fit_summary":
                if rec.get("final_loss") is not None:
                    final_loss = _first(rec.get("final_loss"))
        rows.append({
            "run": i + 1,
            "t_start": run[0].get("t"),
            "records": len(run),
            "events": events,
            "last_step": steps,
            "final_loss": final_loss,
            "config_digest": run[0].get("config_digest")
            if run[0].get("event") == "run" else None,
        })
    return rows


def summarize(records: list, run=None) -> dict:
    """Fold a record stream into per-section summaries (dict, so tests
    and dashboards can consume it without parsing rendered text).

    A JSONL file reused across invocations holds several runs
    (``JsonlSink`` appends); each ``run`` header starts a new one.
    Mixing them would stitch one run's first loss to another's final
    loss and compute steps/s across the idle gap — so a single run is
    summarized, with ``runs_in_file`` recording how many the file
    holds.  ``run`` selects which: 1-based from the front, negative
    from the back, default the LAST (the historical behavior); out of
    range raises ``IndexError``.
    """
    runs = split_runs(records)
    n_runs = len(runs)
    if n_runs:
        if run is None:
            run = -1
        elif run == 0:
            raise IndexError("run selection is 1-based (or negative "
                             "from the end); got 0")
        index = run - 1 if run > 0 else n_runs + run
        if not 0 <= index < n_runs:
            raise IndexError(
                f"run {run} out of range: file holds {n_runs} run(s)")
        records = runs[index]
    out: dict = {}
    if n_runs:
        out["runs_in_file"] = n_runs
        out["run_index"] = index + 1
    by_event: dict = {}
    for rec in records:
        by_event.setdefault(rec.get("event", "?"), []).append(rec)

    runs = by_event.get("run", [])
    if runs:
        out["run"] = runs[0]

    # -- fit curve (in-graph adam taps and host-loop equivalents) ------
    fit = by_event.get("adam", [])
    if fit:
        first, last = fit[0], fit[-1]
        sec = {
            "records": len(fit),
            "first_step": first.get("step"),
            "last_step": last.get("step"),
            "first_loss": _first(first.get("loss")),
            "final_loss": _first(last.get("loss")),
            "final_grad_norm": _first(last.get("grad_norm")),
        }
        dt = last.get("t", 0) - first.get("t", 0)
        dstep = (last.get("step") or 0) - (first.get("step") or 0)
        if dt > 0 and dstep > 0:
            sec["steps_per_sec"] = dstep / dt
        out["fit"] = sec
    for rec in by_event.get("fit_summary", []):
        out.setdefault("fit", {}).update(
            {k: v for k, v in rec.items() if k not in ("event", "t")})

    # -- multi-tenant QoS rollup (fit_summary tenant/class stamps) -----
    tagged = [r for r in by_event.get("fit_summary", [])
              if r.get("tenant") is not None
              or r.get("priority_class") is not None]
    if tagged:
        qos: dict = {}
        for rec in tagged:
            key = (str(rec.get("tenant", "default")),
                   str(rec.get("priority_class", "standard")))
            cur = qos.setdefault(key, {"fits": 0, "wait_s_total": 0.0,
                                       "wait_s_max": 0.0})
            cur["fits"] += 1
            wait = rec.get("wait_s")
            if isinstance(wait, (int, float)):
                cur["wait_s_total"] += float(wait)
                cur["wait_s_max"] = max(cur["wait_s_max"],
                                        float(wait))
        out["qos"] = {
            f"{tenant}/{cls}": {
                "fits": v["fits"],
                "mean_wait_s": (v["wait_s_total"] / v["fits"]
                                if v["fits"] else None),
                "max_wait_s": v["wait_s_max"],
            }
            for (tenant, cls), v in sorted(qos.items())}

    # -- per-tenant usage accounting (PR 20 tenant_usage records) ------
    usage_recs = by_event.get("tenant_usage", [])
    if usage_recs:
        usage: dict = {}
        for rec in usage_recs:
            # Records are cumulative ledger snapshots: the LAST one
            # per (tenant, class) is the truth, earlier ones are
            # progress updates.
            key = (str(rec.get("tenant", "default")),
                   str(rec.get("priority_class", "standard")))
            usage[key] = {
                "fits": rec.get("fits"),
                "busy_s": rec.get("busy_s"),
                "sheds": rec.get("sheds"),
                "violations": rec.get("violations"),
            }
        out["usage"] = {f"{tenant}/{cls}": v
                        for (tenant, cls), v in sorted(usage.items())}

    # -- error-budget trail (PR 20 slo_budget records) -----------------
    budget_recs = by_event.get("slo_budget", [])
    if budget_recs:
        budget: dict = {}
        for rec in budget_recs:
            cls = str(rec.get("priority_class", "standard"))
            budget[cls] = {
                "remaining_frac": rec.get("remaining_frac"),
                "burn_rate": rec.get("burn_rate"),
                "fast_burning": rec.get("fast_burning"),
                "violations": rec.get("violations"),
            }
        out["slo_budget"] = dict(sorted(budget.items()))

    # -- sampler (hmc taps) --------------------------------------------
    hmc = by_event.get("hmc", [])
    if hmc:
        last = hmc[-1]
        out["hmc"] = {
            "records": len(hmc),
            "last_step": last.get("step"),
            "accept": _first(last.get("accept")),
            "step_size": _first(last.get("step_size")),
            "divergences": (sum(last["divergences"])
                            if isinstance(last.get("divergences"), list)
                            else last.get("divergences")),
        }

    # -- collective traffic --------------------------------------------
    comm = by_event.get("comm", [])
    if comm:
        last = comm[-1]
        out["comm"] = {k: v for k, v in last.items()
                       if k not in ("event", "t")}

    # -- streaming pipeline --------------------------------------------
    stream = by_event.get("stream", [])
    if stream:
        last = stream[-1]
        out["stream"] = {k: v for k, v in last.items()
                         if k not in ("event", "t")}

    # -- profiler capture / roofline attribution -----------------------
    for event in ("profile", "roofline", "costmodel"):
        recs = by_event.get(event, [])
        if recs:
            out[event] = {k: v for k, v in recs[-1].items()
                          if k not in ("event", "t")}

    # -- distributed traces (trace_span records) -------------------------
    tspans = by_event.get("trace_span", [])
    if tspans:
        trace_ids = set()
        hops: dict = {}
        for rec in tspans:
            if rec.get("trace_id"):
                trace_ids.add(rec["trace_id"])
            if rec.get("parent_span_id") is None:
                continue        # roots are requests, not hops
            name = rec.get("name", "?")
            cur = hops.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            cur["count"] += 1
            elapsed = rec.get("elapsed_s") or 0.0
            cur["total_s"] += elapsed
            cur["max_s"] = max(cur["max_s"], elapsed)
        roots = [r for r in tspans
                 if r.get("parent_span_id") is None]
        slowest = max(roots,
                      key=lambda r: r.get("elapsed_s") or 0.0,
                      default=None)
        out["trace"] = {
            "spans": len(tspans),
            "traces": len(trace_ids),
            "hops": hops,
            "requeues": sum(1 for r in tspans
                            if r.get("name") == "requeue"),
            "slowest": ({"trace_id": slowest.get("trace_id"),
                         "elapsed_s": slowest.get("elapsed_s"),
                         "outcome": slowest.get("outcome")}
                        if slowest is not None else None),
        }

    # -- job pipelines (job_summary + predictive_check records) ----------
    jobs = by_event.get("job_summary", [])
    checks = by_event.get("predictive_check", [])
    if jobs or checks:
        verdicts_by_job: dict = {}
        for rec in checks:
            verdicts_by_job.setdefault(rec.get("job_id"), []).append({
                k: rec.get(k) for k in
                ("stage", "ok", "verdicts", "n_draws", "finite_frac",
                 "median_excess") if rec.get(k) is not None
                or k == "ok"})
        out["job"] = {
            "records": len(jobs),
            "jobs": [{
                "job_id": rec.get("job_id"),
                "ok": rec.get("ok"),
                "elapsed_s": rec.get("elapsed_s"),
                "trace_id": rec.get("trace_id"),
                "n_stages": rec.get("n_stages"),
                "stages": rec.get("stages") or [],
                "checks": verdicts_by_job.get(rec.get("job_id"), []),
            } for rec in jobs],
            # Checks whose job never settled a summary (crashed
            # runner) still surface.
            "orphan_checks": [v for job_id, vs in
                              verdicts_by_job.items()
                              if not any(r.get("job_id") == job_id
                                         for r in jobs)
                              for v in vs],
        }

    # -- spans (total time per name) -------------------------------------
    spans = by_event.get("span", [])
    if spans:
        totals: dict = {}
        for rec in spans:
            name = rec.get("path", rec.get("name", "?"))
            cur = totals.setdefault(name, {"count": 0, "total_s": 0.0})
            cur["count"] += 1
            cur["total_s"] += rec.get("elapsed_s") or 0.0
        out["spans"] = totals

    # -- liveness --------------------------------------------------------
    stalls = by_event.get("stall", [])
    beats = by_event.get("heartbeat", [])
    if stalls or beats:
        out["liveness"] = {
            "heartbeats": len(beats),
            "stalls": len(stalls),
            "max_stalled_s": max(
                (rec.get("stalled_s") or 0.0 for rec in stalls),
                default=0.0),
        }

    # -- bench dossier records -------------------------------------------
    bench = by_event.get("bench", [])
    if bench:
        out["bench"] = {rec.get("config", "?"): rec.get("value")
                        for rec in bench}

    # -- autotuner decisions (why a config was chosen) -------------------
    tune = by_event.get("tune", [])
    if tune:
        chosen = []
        for rec in tune:
            if not rec.get("chosen"):
                continue
            chosen.append({k: rec.get(k) for k in
                           ("key", "scope", "knobs", "predicted_s",
                            "measured_s", "fits_per_hour", "warm")
                           if rec.get(k) is not None})
        out["tune"] = {"records": len(tune), "chosen": chosen}

    out["n_records"] = len(records)
    return out


def render(summary: dict) -> str:
    """The human-readable view of :func:`summarize`'s output."""
    lines = []
    if summary.get("runs_in_file", 0) > 1:
        which = summary.get("run_index")
        lines.append(
            f"(file holds {summary['runs_in_file']} runs; "
            + ("summarizing the last"
               if which in (None, summary["runs_in_file"])
               else f"summarizing run {which}") + ")")
    run = summary.get("run")
    if run:
        lines.append(
            f"run: jax {run.get('jax_version')} / "
            f"jaxlib {run.get('jaxlib_version')}  "
            f"backend={run.get('backend')}  "
            f"devices={run.get('device_count')}x"
            f"{run.get('device_kind')}  "
            f"processes={run.get('process_count')}  "
            f"config={run.get('config_digest')}")
    fit = summary.get("fit")
    if fit:
        if fit.get("records"):
            lines.append(
                f"fit: loss {_fmt(fit.get('first_loss'))} -> "
                f"{_fmt(fit.get('final_loss'))} over steps "
                f"{_fmt(fit.get('first_step'))}.."
                f"{_fmt(fit.get('last_step'))}"
                f"  ({fit['records']} tap records)")
        extras = [f"{k}={_fmt(float(v) if isinstance(v, (int, float)) else v)}"
                  for k, v in fit.items()
                  if k in ("steps_per_sec", "final_grad_norm",
                           "best_loss", "max_rhat", "min_ess",
                           "divergences", "overlap_frac",
                           "postmortem_bundle") and v is not None]
        if not fit.get("records") and fit.get("final_loss") is not None:
            extras.insert(0, f"final_loss={_fmt(fit['final_loss'])}")
        if extras:
            prefix = "     " if fit.get("records") else "fit: "
            lines.append(prefix + "  ".join(extras))
        pass_overlap = fit.get("pass_overlap")
        if isinstance(pass_overlap, dict) and pass_overlap:
            lines.append("     pass overlap: " + "  ".join(
                f"{name}={_fmt(frac)}"
                for name, frac in sorted(pass_overlap.items())))
        hops = fit.get("hops")
        if isinstance(hops, dict) and hops:
            # The served fit's per-hop latency vector (FitResult
            # .hops via fit_summary), slowest hop first.
            lines.append("     trace hops: " + "  ".join(
                f"{name}={_fmt(v)}s" for name, v in sorted(
                    hops.items(), key=lambda kv: -(kv[1] or 0)))
                + (f"  [trace {str(fit['trace_id'])[:12]}]"
                   if fit.get("trace_id") else ""))
    qos = summary.get("qos")
    if qos:
        lines.append("qos (tenant/class): " + "  ".join(
            f"{key}: {v['fits']} fits, "
            f"wait mean={_fmt(v.get('mean_wait_s'))}s "
            f"max={_fmt(v.get('max_wait_s'))}s"
            for key, v in qos.items()))
    usage = summary.get("usage")
    if usage:
        lines.append("usage (tenant/class): " + "  ".join(
            f"{key}: {v.get('fits')} fits, "
            f"busy={_fmt(v.get('busy_s'))}s, "
            f"shed={v.get('sheds')}, viol={v.get('violations')}"
            for key, v in usage.items()))
    budget = summary.get("slo_budget")
    if budget:
        lines.append("slo budget: " + "  ".join(
            f"{cls}: {_fmt((v.get('remaining_frac') or 0) * 100)}% "
            f"left, burn={_fmt(v.get('burn_rate'))}"
            + ("!" if v.get("fast_burning") else "")
            for cls, v in budget.items()))
    hmc = summary.get("hmc")
    if hmc:
        lines.append(
            f"hmc: accept={_fmt(hmc.get('accept'))}  "
            f"step_size={_fmt(hmc.get('step_size'))}  "
            f"divergences={_fmt(hmc.get('divergences'))}  "
            f"({hmc.get('records', 0)} tap records)")
    comm = summary.get("comm")
    if comm:
        by_op = comm.get("bytes_by_op") or {}
        ops = "  ".join(f"{k}={v}B" for k, v in sorted(by_op.items()))
        lines.append(
            f"comm: {_fmt(comm.get('bytes_per_step'))} bytes/step "
            f"({_fmt(comm.get('calls_per_step'))} collective calls)"
            + (f"  [{ops}]" if ops else ""))
    stream = summary.get("stream")
    if stream:
        lines.append(
            f"stream: stall_fraction={_fmt(stream.get('stall_fraction'))}"
            f"  overlap_frac={_fmt(stream.get('overlap_frac'))}"
            f"  chunks/s={_fmt(stream.get('chunks_per_sec'))}"
            f"  bytes={_fmt(stream.get('bytes_streamed'))}"
            f"  max_live_buffers={_fmt(stream.get('max_live_buffers'))}")
        passes = stream.get("passes")
        if isinstance(passes, dict) and passes:
            for name, per in sorted(passes.items()):
                lines.append(
                    f"  pass {name}: "
                    f"stall_fraction={_fmt(per.get('stall_fraction'))}"
                    f"  overlap_frac={_fmt(per.get('overlap_frac'))}"
                    f"  chunks={_fmt(per.get('chunks'))}"
                    f"  bytes={_fmt(per.get('bytes_streamed'))}")
    profile = summary.get("profile")
    if profile:
        lines.append(
            f"profile: device={_fmt(profile.get('total_device_us'))}us"
            + (f"  per_step={_fmt(profile.get('per_step_us'))}us"
               if profile.get("per_step_us") is not None else "")
            + (f"  roofline_frac={_fmt(profile.get('roofline_frac'))}"
               f" ({profile.get('bound')}-bound)"
               if profile.get("roofline_frac") is not None else "")
            + (f"  rtt={_fmt(profile.get('tunnel_rtt_ms'))}ms"
               if profile.get("tunnel_rtt_ms") is not None else ""))
        for op in (profile.get("top_ops") or [])[:5]:
            lines.append(f"  {op.get('frac', 0):7.1%}  "
                         f"{_fmt(op.get('us'))}us  x{op.get('count')}"
                         f"  {str(op.get('op'))[:70]}")
    roofline = summary.get("roofline")
    if roofline:
        lines.append(
            f"roofline: predicted={_fmt(roofline.get('predicted_s'))}s"
            f"  measured={_fmt(roofline.get('measured_s'))}s"
            f"  frac={_fmt(roofline.get('roofline_frac'))}"
            f"  ({roofline.get('bound')}-bound, "
            f"{roofline.get('device_kind')})")
    trace = summary.get("trace")
    if trace:
        lines.append(
            f"trace: {trace['traces']} traces / {trace['spans']} "
            f"spans"
            + (f", {trace['requeues']} requeue hops"
               if trace.get("requeues") else ""))
        slowest = trace.get("slowest")
        if slowest:
            lines.append(
                f"  slowest: {str(slowest.get('trace_id'))[:12]}  "
                f"{_fmt(slowest.get('elapsed_s'))}s  "
                f"outcome={slowest.get('outcome')}  "
                "(waterfall: python -m multigrad_tpu.telemetry"
                ".trace --trace <id>)")
        for name, cur in sorted(trace["hops"].items(),
                                key=lambda kv: -kv[1]["total_s"]):
            lines.append(
                f"  hop {name}: x{cur['count']}  "
                f"total {_fmt(cur['total_s'])}s  "
                f"max {_fmt(cur['max_s'])}s")
    job = summary.get("job")
    if job:
        for j in job.get("jobs", []):
            lines.append(
                f"job: {j.get('job_id')}  "
                + ("ok" if j.get("ok") else "FAILED")
                + f"  {_fmt(j.get('elapsed_s'))}s  "
                f"{j.get('n_stages')} stages"
                + (f"  [trace {str(j['trace_id'])[:12]}]"
                   if j.get("trace_id") else ""))
            for st in j.get("stages", []):
                extra = ""
                if st.get("n_fits"):
                    extra += f"  fits={st['n_fits']}"
                if (st.get("attempts") or 1) > 1:
                    extra += f"  attempts={st['attempts']}"
                if st.get("error"):
                    extra += f"  error={str(st['error'])[:50]}"
                lines.append(
                    f"  stage {st.get('stage')}: "
                    f"{st.get('outcome')}  "
                    f"{_fmt(st.get('elapsed_s'))}s" + extra)
            for chk in j.get("checks", []):
                verdicts = chk.get("verdicts") or {}
                lines.append(
                    f"  check {chk.get('stage')}: "
                    + ("ok" if chk.get("ok") else "FAILED")
                    + ("  " + "  ".join(
                        f"{k}={'ok' if v else 'FAIL'}"
                        for k, v in sorted(verdicts.items()))
                       if verdicts else "")
                    + (f"  draws={chk['n_draws']}"
                       if chk.get("n_draws") is not None else ""))
        for chk in job.get("orphan_checks", []):
            lines.append(
                f"job: (unsettled)  check {chk.get('stage')}: "
                + ("ok" if chk.get("ok") else "FAILED"))
    spans = summary.get("spans")
    if spans:
        parts = [f"{name}={cur['total_s']:.3f}s(x{cur['count']})"
                 for name, cur in sorted(spans.items())]
        lines.append("spans: " + "  ".join(parts))
    liveness = summary.get("liveness")
    if liveness:
        lines.append(
            f"liveness: {liveness['heartbeats']} heartbeats, "
            f"{liveness['stalls']} stalls "
            f"(max {_fmt(liveness['max_stalled_s'])}s)")
    tune = summary.get("tune")
    if tune:
        lines.append(f"tune: {tune.get('records', 0)} candidate "
                     f"records, {len(tune.get('chosen', []))} chosen")
        for ch in tune.get("chosen", []):
            knobs = ch.get("knobs")
            lines.append(
                f"  {ch.get('key')} -> "
                + (json.dumps(knobs) if isinstance(knobs,
                                                   (dict, list))
                   else str(knobs))
                + f"  predicted={_fmt(ch.get('predicted_s'))}s"
                  f"  measured={_fmt(ch.get('measured_s'))}s"
                + ("  (warm: zero trials)" if ch.get("warm")
                   else ""))
    bench = summary.get("bench")
    if bench:
        lines.append("bench configs:")
        for name, value in bench.items():
            lines.append(f"  {name} = "
                         + (json.dumps(value)
                            if isinstance(value, (dict, list))
                            else _fmt(value)))
    if not lines:
        lines.append("(no recognized telemetry records)")
    lines.append(f"records: {summary.get('n_records', 0)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.report",
        description="Summarize a multigrad_tpu telemetry JSONL stream.")
    parser.add_argument("paths", nargs="+",
                        help="telemetry .jsonl file(s)")
    parser.add_argument("--json", action="store_true",
                        help="emit the summary as JSON instead of text")
    parser.add_argument("--run", type=int, default=None, metavar="N",
                        help="which run of an appended multi-run file "
                             "to summarize (1-based; negative counts "
                             "from the end; default: the last)")
    parser.add_argument("--list-runs", action="store_true",
                        help="list the runs an appended file holds "
                             "instead of summarizing one")
    args = parser.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            records = load_records(path)
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 1
            continue
        if len(args.paths) > 1 and not args.json:
            print(f"== {path} ==")
        if args.list_runs:
            rows = list_runs(records)
            if args.json:
                print(json.dumps({"path": path, "runs": rows},
                                 indent=1))
                continue
            for row in rows:
                events = "  ".join(
                    f"{k}={v}" for k, v in sorted(row["events"].items()))
                print(f"run {row['run']}: {row['records']} records"
                      + (f", last step {row['last_step']}"
                         if row["last_step"] is not None else "")
                      + (f", final loss {_fmt(row['final_loss'])}"
                         if row["final_loss"] is not None else "")
                      + f"  [{events}]")
            continue
        try:
            summary = summarize(records, run=args.run)
        except IndexError as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(json.dumps({"path": path, **summary}, indent=1))
        else:
            print(render(summary))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
