"""Cross-rank telemetry aggregation: merge, skew, stragglers.

Under multi-host SPMD every process writes its own JSONL stream
(rank-gated taps write on process 0 only, but spans, heartbeats,
stream counters and stalls are per-host facts).  This module turns a
pile of per-rank files into one fleet view::

    python -m multigrad_tpu.telemetry.aggregate rank*.jsonl
    python -m multigrad_tpu.telemetry.aggregate --json rank*.jsonl
    python -m multigrad_tpu.telemetry.aggregate --out merged.jsonl ...

Every record carries ``process_index`` (stamped by
:class:`~multigrad_tpu.telemetry.MetricsLogger` since the flight-
recorder PR), so merged streams stay attributable.  The aggregation:

* **per-rank summary** — record counts, wall span, heartbeat/stall
  totals per process;
* **span skew** — for every span path that appears on ≥ 2 ranks, the
  start/end spread across ranks (span records carry exit time ``t``
  and ``elapsed_s``, so both endpoints are reconstructible);
* **straggler detection** — ranks whose span end lags the fleet
  median by more than ``threshold_s`` (default) or
  ``threshold_frac`` × the median duration, whichever is larger —
  the pjit-pod debugging workflow's first question ("which host is
  late?") answered from artifact files alone.

The CLI path is pure stdlib (same caveat as ``telemetry.report``:
``-m`` imports the package and therefore jax; run the file directly
on a jax-less triage box).  :func:`gather_to_rank0` is the in-job
collection helper: it ships each process's records to process 0 over
the jax distributed runtime, for jobs whose hosts lack a shared
filesystem.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

__all__ = ["load_rank_records", "merge_records", "rank_summary",
           "span_skew", "find_stragglers", "gather_to_rank0",
           "aggregate", "merge_traces", "main"]


def _load_jsonl(path: str) -> list:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue            # truncated tail: skip, don't die
    return records


def load_rank_records(paths: Sequence[str]) -> list:
    """Read per-rank JSONL files into one stamped record list.

    Records missing ``process_index`` (pre-stamp streams) inherit it
    from their file's run record, else the file's position in
    ``paths`` — so legacy files still merge deterministically.
    """
    merged = []
    for i, path in enumerate(paths):
        records = _load_jsonl(path)
        default = i
        for rec in records:
            if rec.get("event") == "run" \
                    and rec.get("process_index") is not None:
                default = rec["process_index"]
                break
        for rec in records:
            if rec.get("process_index") is None:
                rec = dict(rec, process_index=default)
            merged.append(rec)
    return merged


def merge_records(records: list) -> list:
    """Stable time-ordered merge (records without ``t`` sort last,
    preserving their relative order)."""
    return sorted(records, key=lambda r: (r.get("t") is None,
                                          r.get("t") or 0.0))


def rank_summary(records: list) -> dict:
    """Per-rank record accounting: counts, wall span, liveness."""
    by_rank: dict = {}
    for rec in records:
        rank = rec.get("process_index", 0)
        cur = by_rank.setdefault(rank, {
            "records": 0, "first_t": None, "last_t": None,
            "heartbeats": 0, "stalls": 0, "events": {}})
        cur["records"] += 1
        t = rec.get("t")
        if t is not None:
            cur["first_t"] = t if cur["first_t"] is None \
                else min(cur["first_t"], t)
            cur["last_t"] = t if cur["last_t"] is None \
                else max(cur["last_t"], t)
        event = rec.get("event", "?")
        cur["events"][event] = cur["events"].get(event, 0) + 1
        if event == "heartbeat":
            cur["heartbeats"] += 1
        elif event == "stall":
            cur["stalls"] += 1
    for cur in by_rank.values():
        if cur["first_t"] is not None and cur["last_t"] is not None:
            cur["wall_s"] = round(cur["last_t"] - cur["first_t"], 3)
    return by_rank


def _median(values: List[float]) -> float:
    values = sorted(values)
    n = len(values)
    mid = n // 2
    return values[mid] if n % 2 else 0.5 * (values[mid - 1]
                                            + values[mid])


def span_skew(records: list) -> dict:
    """Cross-rank start/end spread per span path.

    Only spans seen on ≥ 2 distinct ranks are reported (a rank-0-only
    span has no skew to measure).  Multiple occurrences of a path on
    one rank keep the LAST one — the steady-state occurrence, which
    is what straggler analysis wants.
    """
    per_path: dict = {}
    for rec in records:
        if rec.get("event") != "span":
            continue
        t = rec.get("t")
        elapsed = rec.get("elapsed_s")
        if t is None or elapsed is None:
            continue
        path = rec.get("path", rec.get("name", "?"))
        rank = rec.get("process_index", 0)
        per_path.setdefault(path, {})[rank] = {
            "start": t - elapsed, "end": t,
            "elapsed_s": elapsed}
    out = {}
    for path, ranks in per_path.items():
        if len(ranks) < 2:
            continue
        starts = [v["start"] for v in ranks.values()]
        ends = [v["end"] for v in ranks.values()]
        out[path] = {
            "ranks": sorted(ranks),
            "start_spread_s": round(max(starts) - min(starts), 4),
            "end_spread_s": round(max(ends) - min(ends), 4),
            "median_elapsed_s": round(_median(
                [v["elapsed_s"] for v in ranks.values()]), 4),
            "per_rank": {r: {"start": round(v["start"], 4),
                             "end": round(v["end"], 4),
                             "elapsed_s": round(v["elapsed_s"], 4)}
                         for r, v in sorted(ranks.items())},
        }
    return out


def find_stragglers(skew: dict, threshold_s: float = 1.0,
                    threshold_frac: float = 0.2) -> list:
    """Ranks whose span END lags the fleet median.

    A rank straggles on a span when ``end - median(end)`` exceeds
    ``max(threshold_s, threshold_frac · median_elapsed)`` — the
    absolute floor keeps sub-second jitter quiet, the fractional
    term scales with long spans.  Returns a list of findings.
    """
    findings = []
    for path, info in skew.items():
        ends = {r: v["end"] for r, v in info["per_rank"].items()}
        med = _median(list(ends.values()))
        limit = max(threshold_s,
                    threshold_frac * info["median_elapsed_s"])
        for rank, end in sorted(ends.items()):
            lag = end - med
            if lag > limit:
                findings.append({
                    "span": path, "rank": rank,
                    "lag_s": round(lag, 4),
                    "limit_s": round(limit, 4),
                    "median_end": round(med, 4)})
    return findings


def aggregate(paths: Sequence[str], threshold_s: float = 1.0,
              threshold_frac: float = 0.2) -> dict:
    """The whole pipeline: load → merge → summarize → skew →
    stragglers (the CLI's machine-readable output)."""
    merged = merge_records(load_rank_records(paths))
    skew = span_skew(merged)
    return {
        "files": list(paths),
        "n_records": len(merged),
        "n_traces": len({r.get("trace_id") for r in merged
                         if r.get("event") == "trace_span"
                         and r.get("trace_id")}),
        "ranks": rank_summary(merged),
        "span_skew": skew,
        "stragglers": find_stragglers(skew, threshold_s,
                                      threshold_frac),
    }


def merge_traces(paths: Sequence[str]) -> dict:
    """Merge per-process trace JSONLs by ``trace_id``.

    The cross-process assembly step of distributed request tracing
    (:mod:`.tracing`): the router and every fleet worker write their
    own ``trace_span`` stream; grouping the union by ``trace_id``
    reconstructs each request's full hop waterfall — including a
    SIGKILL'd worker's partial spans next to the survivor's, since
    the line-atomic per-process files survive the death.  Returns
    ``{trace_id: [span records sorted by start]}``; render with
    ``python -m multigrad_tpu.telemetry.trace`` (whose
    :func:`~multigrad_tpu.telemetry.trace.trace_summary` adds the
    completeness/coverage verdicts).
    """
    from .trace import group_traces, load_spans
    return group_traces(load_spans(paths))


def gather_to_rank0(records: list) -> Optional[list]:
    """Collect every process's records onto process 0 in-job.

    Serializes the local records to JSON bytes and all-gathers them
    as padded uint8 arrays over the jax distributed runtime (no
    shared filesystem needed).  Returns the merged stamped list on
    process 0 and ``None`` elsewhere; single-process jobs get their
    local records back unchanged.
    """
    import jax

    if jax.process_count() == 1:
        return merge_records([dict(r) for r in records])

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    blob = json.dumps(records).encode()
    n = np.array([len(blob)], np.int32)
    lengths = np.asarray(multihost_utils.process_allgather(n)).ravel()
    pad = int(lengths.max())
    buf = np.zeros(pad, np.uint8)
    buf[:len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf)))
    if jax.process_index() != 0:
        return None
    merged = []
    for rank, (length, row) in enumerate(zip(lengths, gathered)):
        recs = json.loads(bytes(row[:int(length)]).decode())
        for rec in recs:
            if rec.get("process_index") is None:
                rec = dict(rec, process_index=rank)
            merged.append(rec)
    return merge_records(merged)


def render(summary: dict) -> str:
    """Human-readable fleet view of :func:`aggregate`'s output."""
    lines = [f"{len(summary['files'])} rank files, "
             f"{summary['n_records']} records"
             + (f", {summary['n_traces']} request traces "
                "(render: python -m multigrad_tpu.telemetry.trace)"
                if summary.get("n_traces") else "")]
    for rank, cur in sorted(summary["ranks"].items()):
        events = "  ".join(f"{k}={v}" for k, v
                           in sorted(cur["events"].items()))
        wall = cur.get("wall_s")
        lines.append(
            f"rank {rank}: {cur['records']} records"
            + (f" over {wall}s" if wall is not None else "")
            + (f", {cur['stalls']} stalls" if cur["stalls"] else "")
            + f"  [{events}]")
    for path, info in sorted(summary["span_skew"].items()):
        lines.append(
            f"span {path}: end spread {info['end_spread_s']}s over "
            f"ranks {info['ranks']} "
            f"(median {info['median_elapsed_s']}s)")
    if summary["stragglers"]:
        for s in summary["stragglers"]:
            lines.append(
                f"STRAGGLER rank {s['rank']} on span {s['span']}: "
                f"{s['lag_s']}s behind the median "
                f"(limit {s['limit_s']}s)")
    else:
        lines.append("no stragglers detected")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.aggregate",
        description="Merge per-rank telemetry JSONLs; detect span "
                    "skew and stragglers.")
    parser.add_argument("paths", nargs="+",
                        help="per-rank telemetry .jsonl files")
    parser.add_argument("--json", action="store_true",
                        help="emit the aggregate as JSON")
    parser.add_argument("--out", default=None,
                        help="also write the merged stamped stream "
                             "to this JSONL path")
    parser.add_argument("--threshold-s", type=float, default=1.0,
                        help="absolute straggler lag floor (s)")
    parser.add_argument("--threshold-frac", type=float, default=0.2,
                        help="straggler lag as a fraction of the "
                             "median span duration")
    args = parser.parse_args(argv)
    try:
        summary = aggregate(args.paths, args.threshold_s,
                            args.threshold_frac)
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 1
    if args.out:
        merged = merge_records(load_rank_records(args.paths))
        with open(args.out, "w") as f:
            for rec in merged:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
