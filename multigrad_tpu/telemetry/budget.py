"""SLO error budgets and multi-window burn-rate alerting.

PR 17's :class:`~multigrad_tpu.serve.slo.SloMonitor` renders a live
verdict — "interactive p95 is 0.41 s against a 0.5 s SLO" — but a
verdict has no memory: it cannot say how much violation headroom is
*left*, nor how fast it is being consumed.  This module adds both,
the SRE way:

* an :class:`~multigrad_tpu.serve.slo.Slo` carries an
  **allowed-violation budget** (default ``1 - quantile``: a p95
  objective tolerates 5 % violating requests);
* :class:`SloBudget` counts good/bad observations in a
  :class:`~multigrad_tpu.telemetry.rollup.RollupStore` and derives,
  over a rolling compliance window,

  - ``remaining_frac`` — the unspent budget fraction,
  - ``burn_rate`` — violation fraction over a window divided by the
    budget (1.0 = burning exactly at the sustainable rate), tracked
    over **multi-window pairs** (fast 5 m/1 h and slow 1 h/6 h — the
    Google SRE workbook shape: the short window catches the fire,
    the long window stops a single spike from paging),
  - ``exhaustion_eta_s`` — seconds until the budget hits zero at the
    current fast burn;

* the three land as ``multigrad_slo_budget_*`` gauges (labelled by
  ``priority_class``), budget-burning fits additionally observe into
  ``multigrad_slo_budget_violation_seconds`` with their **trace id
  as the exemplar** — from a burning budget straight to an offending
  trace;
* :class:`BurnRateAlert` is a PR-9 :class:`~multigrad_tpu.telemetry
  .alerts.AlertRule`: rising-edge, one ``alert`` record per burn
  episode, wired into any :class:`~multigrad_tpu.telemetry.alerts
  .AlertEngine` next to the default rules.

Pure stdlib at module level, per the telemetry package contract;
never imports :mod:`multigrad_tpu.serve` (the serve layer constructs
budgets from its ``Slo`` objects, not the other way around).
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from .alerts import AlertRule
from .rollup import RollupStore

__all__ = ["SloBudget", "BurnRateAlert",
           "FAST_WINDOWS", "SLOW_WINDOWS",
           "FAST_BURN_THRESHOLD", "SLOW_BURN_THRESHOLD"]

#: Multi-window burn pairs (seconds) and page thresholds — the SRE
#: workbook's 5 m/1 h fast pair at 14.4× and 1 h/6 h slow pair at 6×.
FAST_WINDOWS = (300.0, 3600.0)
SLOW_WINDOWS = (3600.0, 21600.0)
FAST_BURN_THRESHOLD = 14.4
SLOW_BURN_THRESHOLD = 6.0


class SloBudget:
    """Error-budget ledger for one priority class.

    Parameters
    ----------
    priority_class : str
        The class this ledger covers (gauge label).
    threshold_s : float
        Latency objective — an observation above it burns budget.
    budget : float
        Allowed violating fraction over the compliance window
        (``0.05`` = 5 %).
    live : LiveMetrics, optional
        Registry the gauges/exemplars export into.
    window_s : float
        Rolling compliance window the remaining fraction is computed
        over (default 6 h — the slow pair's long window, i.e. the
        store's full retention).
    clock : callable
        Injected time source (tests hand-compute against a fake
        clock).
    """

    def __init__(self, priority_class: str, threshold_s: float,
                 budget: float = 0.05, live=None,
                 window_s: float = 21600.0,
                 fast_threshold: float = FAST_BURN_THRESHOLD,
                 slow_threshold: float = SLOW_BURN_THRESHOLD,
                 clock=time.time):
        if not (0.0 < float(budget) <= 1.0):
            raise ValueError(
                f"budget must be in (0, 1], got {budget}")
        self.priority_class = str(priority_class)
        self.threshold_s = float(threshold_s)
        self.budget = float(budget)
        self.window_s = float(window_s)
        self.fast_threshold = float(fast_threshold)
        self.slow_threshold = float(slow_threshold)
        self._clock = clock
        # The ledger IS a rollup store: two counter series, windows
        # and retention for free.  10 s base windows resolve the 5 m
        # fast pair; the 10 m tier's 48-ring covers the 6 h window.
        self._store = RollupStore(clock=clock)
        self._live = live
        self._labels = {"priority_class": self.priority_class}
        self._export()

    # ---------------------------------------------------------- #
    # feeding
    # ---------------------------------------------------------- #
    def observe(self, e2e_s: float,
                trace_id: Optional[str] = None,
                t: Optional[float] = None):
        """Fold one served request; latency above the objective
        burns budget (and exports the trace id as the violation
        exemplar)."""
        bad = float(e2e_s) > self.threshold_s
        self._store.inc("total", 1.0, t=t)
        if bad:
            self._store.inc("bad", 1.0, t=t)
            if self._live is not None:
                self._live.observe(
                    "multigrad_slo_budget_violation_seconds",
                    float(e2e_s), labels=dict(self._labels),
                    exemplar=trace_id,
                    help="latency of budget-burning fits "
                         "(exemplar: trace id)")
        self._export(t=t)

    def record_shed(self, t: Optional[float] = None):
        """A shed request is a violated request: it burns budget."""
        self._store.inc("total", 1.0, t=t)
        self._store.inc("bad", 1.0, t=t)
        self._export(t=t)

    # ---------------------------------------------------------- #
    # arithmetic
    # ---------------------------------------------------------- #
    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """Violating fraction over the window divided by the budget;
        ``None`` with no traffic in the window."""
        total = self._store.delta("total", window_s, now=now)
        if not total:
            return None
        bad = self._store.delta("bad", window_s, now=now) or 0.0
        return (bad / total) / self.budget

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The budget state, hand-computable: ``remaining_frac =
        1 - bad/(total*budget)`` over the compliance window;
        ``burn_rate`` is the fast pair's short window;
        ``exhaustion_eta_s = remaining_frac * window_s / burn_rate``
        (the time to spend what's left at the current pace)."""
        now = self._clock() if now is None else now
        total = self._store.delta("total", self.window_s,
                                  now=now) or 0.0
        bad = self._store.delta("bad", self.window_s, now=now) or 0.0
        if total > 0:
            spent = bad / (total * self.budget)
            remaining = max(0.0, 1.0 - spent)
        else:
            remaining = 1.0
        fast_short = self.burn_rate(FAST_WINDOWS[0], now=now)
        fast_long = self.burn_rate(FAST_WINDOWS[1], now=now)
        slow_short = self.burn_rate(SLOW_WINDOWS[0], now=now)
        slow_long = self.burn_rate(SLOW_WINDOWS[1], now=now)
        burn = fast_short if fast_short is not None else 0.0
        eta = None
        if burn > 0 and remaining > 0:
            eta = remaining * self.window_s / burn
        elif remaining <= 0:
            eta = 0.0
        return {
            "priority_class": self.priority_class,
            "budget": self.budget,
            "total": int(total), "violations": int(bad),
            "remaining_frac": remaining,
            "burn_rate": burn,
            "burn_rate_fast": (fast_short, fast_long),
            "burn_rate_slow": (slow_short, slow_long),
            "fast_burning": self._pair_burning(
                fast_short, fast_long, self.fast_threshold),
            "slow_burning": self._pair_burning(
                slow_short, slow_long, self.slow_threshold),
            "exhaustion_eta_s": eta,
        }

    @staticmethod
    def _pair_burning(short, long, threshold) -> bool:
        """A pair pages only when BOTH windows exceed the threshold —
        the long window vetoes one-spike pages, the short window ends
        the alert promptly once the fire is out."""
        return (short is not None and long is not None
                and short > threshold and long > threshold)

    def fast_burning(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        return self._pair_burning(
            self.burn_rate(FAST_WINDOWS[0], now=now),
            self.burn_rate(FAST_WINDOWS[1], now=now),
            self.fast_threshold)

    # ---------------------------------------------------------- #
    # export
    # ---------------------------------------------------------- #
    def _export(self, t: Optional[float] = None):
        if self._live is None:
            return
        snap = self.snapshot(now=t)
        self._live.set("multigrad_slo_budget_remaining_frac",
                       snap["remaining_frac"],
                       labels=dict(self._labels),
                       help="unspent error-budget fraction over "
                            "the compliance window")
        self._live.set("multigrad_slo_budget_burn_rate",
                       snap["burn_rate"],
                       labels=dict(self._labels),
                       help="fast-window burn rate (1.0 = "
                            "sustainable pace)")
        self._live.set("multigrad_slo_budget_fast_burning",
                       1.0 if snap["fast_burning"] else 0.0,
                       labels=dict(self._labels),
                       help="1 when the fast multi-window pair "
                            "exceeds its page threshold")
        if snap["exhaustion_eta_s"] is not None:
            self._live.set("multigrad_slo_budget_exhaustion_eta_s",
                           snap["exhaustion_eta_s"],
                           labels=dict(self._labels),
                           help="seconds to budget exhaustion at "
                                "the current burn")


class BurnRateAlert(AlertRule):
    """Rising-edge alert over a set of :class:`SloBudget` ledgers.

    Evaluated on every record the :class:`~multigrad_tpu.telemetry
    .alerts.AlertEngine` sees; the condition HOLDS while any class's
    fast multi-window pair exceeds its threshold, so the base class's
    edge filter yields exactly one ``alert`` record per burn episode
    (re-armed when every class stops burning).

    Parameters
    ----------
    budgets : mapping or object with ``.budgets``
        ``{priority_class: SloBudget}`` — pass a ``SloMonitor``
        directly, its ``budgets`` attribute is picked up.
    """

    name = "slo_burn_rate"

    def __init__(self, budgets, action=None, escalate: bool = False):
        super().__init__(action=action, escalate=escalate)
        self._budgets = budgets

    def _ledgers(self) -> Dict[str, SloBudget]:
        b = getattr(self._budgets, "budgets", self._budgets)
        return b if isinstance(b, dict) else {}

    def check(self, record: dict) -> Optional[dict]:
        burning = {}
        for cls, ledger in self._ledgers().items():
            try:
                if ledger.fast_burning():
                    snap = ledger.snapshot()
                    burning[cls] = {
                        "burn_rate": round(snap["burn_rate"], 3),
                        "remaining_frac": round(
                            snap["remaining_frac"], 4),
                        "exhaustion_eta_s": (
                            round(snap["exhaustion_eta_s"], 1)
                            if snap["exhaustion_eta_s"] is not None
                            else None),
                    }
            except Exception:
                # A broken ledger must not take down the alert
                # engine's whole rule set; skip it this record.
                continue
        if not burning:
            return None
        return {"classes": burning,
                "threshold": FAST_BURN_THRESHOLD,
                "windows_s": list(FAST_WINDOWS)}
