"""Declarative non-fatal alert rules over the live record stream.

The flight recorder (:mod:`.flight`) handles *fatal* anomalies — NaN
loss, wedged hosts — after the fact.  This module is the soft layer
in front of it: rules that watch the record stream **while the fit
runs** and emit ``alert`` records (plus an optional callback action)
the moment a fit stops making progress, without killing runs that are
merely slow or unlucky:

* :class:`LossPlateau` — the EMA of the tapped loss stops moving
  (|slope| below a relative threshold);
* :class:`GradExplosion` — |grad| jumps far above its trailing
  median;
* :class:`ThroughputDrop` — steps/s (from tap-record spacing) falls
  below a fraction of its trailing median — the single-host shadow of
  the straggler check in :mod:`.aggregate`;
* :class:`DivergenceRate` — the HMC sampler's cumulative divergence
  count grows faster than ``max_rate`` per draw;
* :class:`HeartbeatStall` — a ``stall`` record flowed by (re-arms on
  ``stall_recovered``).

Rules have rising-edge semantics: one ``alert`` record per episode,
re-armed when the condition clears, so a plateaued fit does not flood
the stream.  An :class:`AlertEngine` is a :class:`~multigrad_tpu
.telemetry.MetricsLogger` **sink**; pass it as ``alerts=`` to any fit
entry point (or add it to the logger yourself) and fired alerts are
logged back into the same stream — the JSONL file, the live
``/status`` endpoint and the terminal dashboard all see them.  With
``flight=`` a firing rule marked ``escalate=True`` also trips the
:class:`~multigrad_tpu.telemetry.flight.FlightRecorder` (non-fatal:
a postmortem bundle is dumped, the fit continues).

::

    engine = AlertEngine(flight=recorder)          # default rule set
    model.run_adam(guess, nsteps, telemetry=log, log_every=20,
                   alerts=engine)
    engine.alerts        # the fired alert records, host-side

Pure stdlib at module level, per the telemetry package contract.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

__all__ = ["AlertRule", "LossPlateau", "GradExplosion",
           "ThroughputDrop", "DivergenceRate", "HeartbeatStall",
           "default_rules", "AlertEngine"]


def _scalar(v):
    """Scalar view of a tap value (batched fits emit lists): the mean
    over members, so a single diverging ensemble member still moves
    the rule inputs."""
    if isinstance(v, (list, tuple)):
        vals = [float(x) for x in v
                if isinstance(x, (int, float))]
        return sum(vals) / len(vals) if vals else None
    return float(v) if isinstance(v, (int, float)) else None


def _median(values):
    values = sorted(values)
    n = len(values)
    if not n:
        return None
    mid = n // 2
    return values[mid] if n % 2 else 0.5 * (values[mid - 1]
                                            + values[mid])


class AlertRule:
    """Base class: stateful record-stream predicate with rising-edge
    firing.

    Subclasses implement :meth:`check`, returning a detail dict while
    the condition HOLDS and ``None`` otherwise; the base class turns
    that level signal into edge-triggered alerts (one per episode).

    Parameters
    ----------
    action : callable, optional
        ``action(alert_record)`` invoked when the rule fires — hook
        for paging, checkpoint forcing, LR scheduling.  Exceptions
        are swallowed (an alert action must never kill the fit).
    escalate : bool
        Also trip the engine's flight recorder (non-fatal bundle
        dump) on firing.
    """

    name = "alert"

    def __init__(self, action: Optional[Callable] = None,
                 escalate: bool = False):
        self.action = action
        self.escalate = bool(escalate)
        self._active = False

    def check(self, record: dict) -> Optional[dict]:
        raise NotImplementedError

    def reset(self):
        """Re-arm and clear trailing state (a new ``run``/``fit_plan``
        record resets every rule)."""
        self._active = False

    def update(self, record: dict) -> Optional[dict]:
        """Engine entry point: edge-filter :meth:`check`'s level
        signal."""
        detail = self.check(record)
        if detail is None:
            self._active = False
            return None
        if self._active:
            return None
        self._active = True
        return detail


class LossPlateau(AlertRule):
    """Loss EMA slope ~ 0: the fit has stopped improving.

    Tracks an exponential moving average of the tapped loss
    (``halflife`` in *records*) and its slope per step between
    consecutive records; fires when ``|slope|`` stays below
    ``rel_slope · (|ema| + eps)`` — a relative threshold, so it works
    for χ² losses in the thousands and log-MSE losses near zero —
    for ``patience`` consecutive records after ``min_records``.
    """

    name = "loss_plateau"

    def __init__(self, rel_slope: float = 1e-4, halflife: float = 10.0,
                 min_records: int = 8, patience: int = 3, **kwargs):
        super().__init__(**kwargs)
        self.rel_slope = float(rel_slope)
        self.decay = 0.5 ** (1.0 / float(halflife))
        self.min_records = int(min_records)
        self.patience = int(patience)
        self.reset()

    def reset(self):
        super().reset()
        self._m = 0.0
        self._n = 0
        self._prev = None               # (step, corrected ema)
        self._flat = 0

    def check(self, record):
        if record.get("event") != "adam":
            return None
        loss = _scalar(record.get("loss"))
        step = record.get("step")
        if loss is None or step is None or loss != loss:
            return None
        self._n += 1
        self._m = self.decay * self._m + (1.0 - self.decay) * loss
        ema = self._m / (1.0 - self.decay ** self._n)
        prev, self._prev = self._prev, (step, ema)
        if prev is None or step <= prev[0]:
            return None
        slope = (ema - prev[1]) / (step - prev[0])
        limit = self.rel_slope * (abs(ema) + 1e-12)
        if self._n >= self.min_records and abs(slope) < limit:
            self._flat += 1
        else:
            self._flat = 0
        if self._flat >= self.patience:
            return {"message": "loss EMA has plateaued",
                    "loss_ema": round(ema, 6),
                    "ema_slope": slope, "slope_limit": limit}
        return None


class GradExplosion(AlertRule):
    """|grad| spikes ``factor``× above its trailing median."""

    name = "grad_explosion"

    def __init__(self, factor: float = 50.0, window: int = 16,
                 min_records: int = 4, **kwargs):
        super().__init__(**kwargs)
        self.factor = float(factor)
        self.window = int(window)
        self.min_records = int(min_records)
        self.reset()

    def reset(self):
        super().reset()
        self._norms: List[float] = []

    def check(self, record):
        if record.get("event") != "adam":
            return None
        g = _scalar(record.get("grad_norm"))
        if g is None or g != g:
            return None
        med = _median(self._norms[-self.window:])
        self._norms.append(g)
        del self._norms[:-self.window - 1]
        if (med is not None and len(self._norms) > self.min_records
                and g > self.factor * max(med, 1e-30)):
            return {"message": "gradient norm exploded",
                    "grad_norm": g, "trailing_median": med,
                    "factor": round(g / max(med, 1e-30), 2)}
        return None


class ThroughputDrop(AlertRule):
    """Steps/s falls below ``frac`` of its trailing median.

    Rates are measured between consecutive ``adam`` records (wall
    time from ``t``, steps from ``step``), so the rule needs no extra
    instrumentation — a slowing host, a saturating prefetch, or a
    competing tenant all show up here first.
    """

    name = "throughput_drop"

    def __init__(self, frac: float = 0.5, window: int = 12,
                 min_records: int = 6, **kwargs):
        super().__init__(**kwargs)
        self.frac = float(frac)
        self.window = int(window)
        self.min_records = int(min_records)
        self.reset()

    def reset(self):
        super().reset()
        self._prev = None               # (t, step)
        self._rates: List[float] = []

    def check(self, record):
        if record.get("event") != "adam":
            return None
        t, step = record.get("t"), record.get("step")
        if t is None or step is None:
            return None
        prev, self._prev = self._prev, (t, step)
        if prev is None or step <= prev[1] or t <= prev[0]:
            return None
        rate = (step - prev[1]) / (t - prev[0])
        med = _median(self._rates[-self.window:])
        self._rates.append(rate)
        del self._rates[:-self.window - 1]
        if (med is not None and len(self._rates) > self.min_records
                and rate < self.frac * med):
            return {"message": "throughput dropped",
                    "steps_per_sec": round(rate, 4),
                    "trailing_median": round(med, 4),
                    "frac": round(rate / med, 4)}
        return None


class DivergenceRate(AlertRule):
    """HMC divergences accumulate faster than ``max_rate`` per draw."""

    name = "divergence_rate"

    def __init__(self, max_rate: float = 0.1, min_draws: int = 20,
                 **kwargs):
        super().__init__(**kwargs)
        self.max_rate = float(max_rate)
        self.min_draws = int(min_draws)
        self.reset()

    def check(self, record):
        if record.get("event") != "hmc":
            return None
        div = record.get("divergences")
        if isinstance(div, (list, tuple)):
            div = sum(float(d) for d in div)
        step = record.get("step")
        if not isinstance(div, (int, float)) or not step:
            return None
        rate = div / step
        if step >= self.min_draws and rate > self.max_rate:
            return {"message": "HMC divergence rate is high",
                    "divergences": div, "draws": step,
                    "rate": round(rate, 4)}
        return None


class HeartbeatStall(AlertRule):
    """A ``stall`` record flowed by (the Heartbeat thread's verdict);
    re-arms on ``stall_recovered``."""

    name = "heartbeat_stall"

    def check(self, record):      # pragma: no cover - update overrides
        return None

    def update(self, record):
        # Stall records are one-per-episode (Heartbeat's contract), so
        # the base class's level->edge filter cannot apply: hold the
        # episode open until a `stall_recovered` record re-arms.
        event = record.get("event")
        if event == "stall_recovered":
            self._active = False
            return None
        if event != "stall":
            return None
        if self._active:
            return None
        self._active = True
        return {"message": "fit loop stalled",
                "stalled_s": record.get("stalled_s")}


def _accepted_kwargs(cls) -> set:
    """Named constructor parameters across ``cls``'s MRO (so a
    rule-specific override is forwarded only where it applies)."""
    import inspect

    names = set()
    for klass in cls.__mro__:
        init = klass.__dict__.get("__init__")
        if init is None:
            continue
        for name, p in inspect.signature(init).parameters.items():
            if name != "self" and p.kind in (p.POSITIONAL_OR_KEYWORD,
                                             p.KEYWORD_ONLY):
                names.add(name)
    return names


def default_rules(**overrides) -> list:
    """One instance of every shipped rule, default thresholds.

    ``overrides`` are forwarded to every constructor that accepts
    them — ``escalate=True`` arms flight-recorder escalation across
    the board, while a rule-specific knob (``rel_slope=1e-3``)
    reaches only its rule instead of raising on the others.
    """
    classes = (LossPlateau, GradExplosion, ThroughputDrop,
               DivergenceRate, HeartbeatStall)
    return [cls(**{k: v for k, v in overrides.items()
                   if k in _accepted_kwargs(cls)})
            for cls in classes]


class AlertEngine:
    """Evaluate alert rules on a record stream (a MetricsLogger sink).

    Every non-``alert`` record is offered to every rule; a firing
    rule's detail becomes an ``alert`` record — logged back into the
    bound stream (so files, the live endpoint and dashboards see it)
    and collected in :attr:`alerts`.  ``run``/``fit_plan`` records
    reset all rule state, so one engine serves a sequence of fits.

    Parameters
    ----------
    rules : sequence of AlertRule, optional
        Default: :func:`default_rules`.
    flight : FlightRecorder, optional
        Escalation target for rules constructed with
        ``escalate=True`` — the trip is non-fatal (bundle dumped,
        fit continues).
    on_alert : callable, optional
        Engine-wide ``on_alert(alert_record)`` hook, called after any
        rule fires (in addition to per-rule ``action``\\ s).

    A broken rule is disabled after its first exception (one
    ``alert`` record with ``severity="error"`` reports it) — alert
    evaluation must never take the fit down with it.
    """

    def __init__(self, rules=None, flight=None,
                 on_alert: Optional[Callable] = None):
        self.rules = list(rules) if rules is not None \
            else default_rules()
        self.flight = flight
        self.on_alert = on_alert
        self.alerts: List[dict] = []
        self._logger = None
        self._dead: set = set()

    def bind_logger(self, logger):
        """Bind the stream alerts are emitted into (the fit drivers'
        ``wire_monitoring`` calls this)."""
        self._logger = logger

    # -- sink protocol ------------------------------------------------------
    def write(self, record: dict):
        event = record.get("event")
        if event == "alert":
            return                       # never react to our own output
        if event in ("run", "fit_plan"):
            for rule in self.rules:
                rule.reset()
        for rule in self.rules:
            if id(rule) in self._dead:
                continue
            try:
                detail = rule.update(record)
            except Exception as e:
                self._dead.add(id(rule))
                self._emit(rule.name, {
                    "message": f"alert rule disabled after error: {e}",
                }, severity="error", record=record, rule=rule,
                    escalate=False)
                continue
            if detail is not None:
                self._emit(rule.name, detail, record=record,
                           rule=rule)

    def close(self):
        pass

    # -- firing -------------------------------------------------------------
    def _emit(self, name: str, detail: dict, record=None, rule=None,
              severity: str = "warning", escalate=None):
        fields = {"rule": name, "severity": severity,
                  "step": (record or {}).get("step"), **detail}
        if self._logger is not None:
            # MetricsLogger's lock is re-entrant, so emitting from
            # inside a sink's write() fans the alert out to every
            # OTHER sink too (the engine ignores `alert` events).
            alert = self._logger.log("alert", **fields)
        else:
            alert = {"event": "alert", "t": time.time(), **fields}
        self.alerts.append(alert)
        do_escalate = (rule.escalate if escalate is None and
                       rule is not None else bool(escalate))
        if self.flight is not None and do_escalate:
            self.flight.trip(f"alert_{name}", fatal=False,
                             step=fields.get("step"), **detail)
        for hook in (getattr(rule, "action", None), self.on_alert):
            if hook is None:
                continue
            try:
                hook(alert)
            except Exception:
                pass                    # actions must never kill a fit
        return alert
