"""Streaming ANSI terminal dashboard over a telemetry JSONL stream.

::

    python -m multigrad_tpu.telemetry.dashboard run.jsonl --follow
    python -m multigrad_tpu.telemetry.dashboard run.jsonl --once

The terminal twin of the live HTTP endpoint (:mod:`.live`): tail a
fit's JSONL file as it is written and render loss/|grad| sparklines,
steps/s, ETA against the fit plan, HMC acceptance/divergence rates,
per-class SLO error budgets (remaining %%, burn rate, ``!`` while
fast-burning — from ``slo_budget`` records), a stall indicator and
any fired alerts — no HTTP, no dependencies, just
the file the fit is already writing (``JsonlSink`` flushes one
complete line per record precisely so this tail is safe).

``--follow`` refreshes in place every ``--interval`` seconds until
interrupted; ``--once`` renders a single deterministic snapshot (no
cursor control codes) — the mode tests and CI use.  Multi-run files
(appended streams) render the LAST run, same convention as
:mod:`.report`.

Pure stdlib, same triage-box caveat as the report CLI: ``-m`` imports
the package (and so jax); on a jax-less box run the file directly
(``python path/to/multigrad_tpu/telemetry/dashboard.py run.jsonl``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

__all__ = ["TailReader", "Collector", "sparkline", "collect",
           "render", "main"]

SPARK_CHARS = "▁▂▃▄▅▆▇█"


class TailReader:
    """Incremental JSONL reader safe against live writers.

    Reads only *complete* lines: bytes after the last newline stay in
    a carry buffer until the writer finishes the line, so a reader
    polling mid-write can never parse a half-written record — the
    follow-mode twin of ``report.load_records``'s truncated-tail
    repair (which this reader also inherits: an unparseable line —
    e.g. a crashed run's torn tail closed off by the next
    ``JsonlSink`` — is skipped, not fatal).  A shrinking file
    (rotation/truncation) resets the reader to the top.
    """

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = b""

    def poll(self) -> list:
        """New complete records since the last poll."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._pos:            # truncated/rotated: start over
            self._pos = 0
            self._buf = b""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        self._buf += data
        records = []
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue                # torn line: skip, don't die
        return records


def sparkline(values, width: int = 40) -> str:
    """Unicode block sparkline of the last ``width`` values (non-
    finite values render as spaces; a flat series renders mid-height)."""
    vals = [float(v) for v in values][-width:]
    finite = [v for v in vals if v == v and abs(v) != float("inf")]
    if not finite:
        return " " * len(vals)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if v != v or abs(v) == float("inf"):
            out.append(" ")
        elif span == 0:
            out.append(SPARK_CHARS[len(SPARK_CHARS) // 2])
        else:
            idx = int((v - lo) / span * (len(SPARK_CHARS) - 1))
            out.append(SPARK_CHARS[idx])
    return "".join(out)


def _scalar(v):
    if isinstance(v, list):
        return float(v[0]) if v else None
    return float(v) if isinstance(v, (int, float)) else None


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0


def _fmt_eta(seconds) -> str:
    if seconds is None:
        return "-"
    seconds = max(0, int(seconds))
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    return f"{h}:{m:02d}:{s:02d}" if h else f"{m}:{s:02d}"


# Trailing points kept per sparkline series: render shows at most
# `width` of them, so the cap only needs to exceed any sane terminal.
_SERIES_CAP = 512


class Collector:
    """Incremental fold of a record stream into the dashboard view.

    ``--follow`` feeds each poll's NEW records into one persistent
    collector, so a frame costs O(new records) and memory stays
    bounded (series keep the trailing :data:`_SERIES_CAP` points) —
    a multi-hour fit never degrades the refresh.  Boundaries reset
    state: a ``run`` record starts a fresh run (only the LAST run of
    an appended file renders, same rationale as ``report.summarize``
    — stitching runs would fabricate a fit curve), and a ``fit_plan``
    record starts a fresh *fit* within the run (so a sequence of fits
    through one logger never shows the previous fit's summary/series
    against the new plan).  Recent alerts survive fit boundaries —
    they are exactly what an operator coming back to the terminal
    needs to see.
    """

    def __init__(self):
        self.runs_in_file = 0
        self.n_records = 0
        self.run = None
        self.alerts: list = []
        self._reset_run()

    def _reset_run(self):
        self.stalled = False
        self.comm = None
        self.resources = None
        self.budgets: dict = {}
        self._reset_fit()

    def _reset_fit(self):
        self.plan = None
        self.summary = None
        self.hmc = None
        self.loss: list = []
        self.grad: list = []
        self.ema: list = []
        self.steps: list = []
        self.ts: list = []

    def feed(self, records):
        for rec in records:
            self._one(rec)
        return self

    def _one(self, rec: dict):
        event = rec.get("event")
        self.n_records += 1
        if event == "run":
            self.runs_in_file += 1
            if self.runs_in_file > 1:      # keep only the last run
                self.n_records = 1
                self.alerts = []
            self.run = rec
            self._reset_run()
        elif event == "fit_plan":
            self._reset_fit()
            self.plan = rec
        elif event == "adam":
            s, v = rec.get("step"), _scalar(rec.get("loss"))
            if s is not None and v is not None:
                self.steps.append(s)
                self.ts.append(rec.get("t"))
                self.loss.append(v)
                g = _scalar(rec.get("grad_norm"))
                if g is not None:
                    self.grad.append(g)
                e = _scalar(rec.get("loss_ema"))
                if e is not None:
                    self.ema.append(e)
                for series in (self.steps, self.ts, self.loss,
                               self.grad, self.ema):
                    del series[:-_SERIES_CAP]
        elif event == "hmc":
            self.hmc = rec
        elif event == "comm":
            self.comm = rec
        elif event == "resource_sample":
            self.resources = rec       # newest wins, like comm
        elif event == "stall":
            self.stalled = True
        elif event == "stall_recovered":
            self.stalled = False
        elif event == "alert":
            self.alerts.append(rec)
            del self.alerts[:-8]
        elif event == "slo_budget":
            # cumulative ledger snapshot: newest per class wins, and
            # like alerts it survives fit boundaries — the budget
            # spans the serving run, not one fit
            cls = rec.get("priority_class")
            if isinstance(cls, str):
                self.budgets[cls] = rec
        elif event == "fit_summary":
            self.summary = rec

    def view(self) -> dict:
        """The dict :func:`render` consumes."""
        # trailing steps/s from record spacing (last ~8 records);
        # timestamps and steps are filtered as PAIRS, so a stream
        # with some t-less records can't mismatch the endpoints
        rate = None
        pairs = [(t, s) for t, s in zip(self.ts[-8:], self.steps[-8:])
                 if t is not None]
        if len(pairs) >= 2 and pairs[-1][0] > pairs[0][0] \
                and pairs[-1][1] > pairs[0][1]:
            rate = (pairs[-1][1] - pairs[0][1]) \
                / (pairs[-1][0] - pairs[0][0])
        nsteps = (self.plan or {}).get("nsteps")
        if self.summary is not None:
            eta = 0.0
        elif rate and nsteps and self.steps:
            eta = max(0, nsteps - 1 - self.steps[-1]) / rate
        else:
            eta = None
        return {
            "runs_in_file": self.runs_in_file,
            "n_records": self.n_records,
            "run": self.run,
            "plan": self.plan,
            "loss": self.loss,
            "grad_norm": self.grad,
            "loss_ema": self.ema,
            "steps": self.steps,
            "steps_per_sec": rate,
            "nsteps": nsteps,
            "eta_s": eta,
            "hmc": self.hmc,
            "comm": self.comm,
            "resources": self.resources,
            "stalled": self.stalled,
            "alerts": self.alerts,
            "budgets": self.budgets,
            "summary": self.summary,
        }


def collect(records: list) -> dict:
    """One-shot fold (the ``--once`` path): feed everything through a
    fresh :class:`Collector` and return its view."""
    return Collector().feed(records).view()


def render(view: dict, width: int = 64) -> str:
    """One dashboard frame (plain text; the follow loop adds cursor
    control around it)."""
    bar_w = max(16, width - 24)
    lines = []
    run = view.get("run")
    if run:
        lines.append(
            f"run  {run.get('backend')}  "
            f"{run.get('device_count')}x{run.get('device_kind')}  "
            f"procs={run.get('process_count')}  "
            f"jax {run.get('jax_version')}")
    if view.get("runs_in_file", 0) > 1:
        lines.append(f"(file holds {view['runs_in_file']} runs; "
                     f"showing the last)")
    plan = view.get("plan") or {}
    steps = view.get("steps") or []
    nsteps = view.get("nsteps")
    if steps:
        head = f"step {steps[-1]}"
        if nsteps:
            frac = min(1.0, (steps[-1] + 1) / nsteps)
            filled = int(frac * bar_w)
            head += (f"/{nsteps}  [" + "#" * filled
                     + "-" * (bar_w - filled) + f"] {frac:4.0%}")
        lines.append(head)
    elif plan:
        lines.append(f"step -/{plan.get('nsteps')}  (no tap records "
                     f"yet)")
    loss = view.get("loss") or []
    if loss:
        lines.append(f"loss   {sparkline(loss, bar_w)}  "
                     f"{_fmt(loss[-1])}")
    ema = view.get("loss_ema") or []
    if ema:
        lines.append(f"ema    {sparkline(ema, bar_w)}  "
                     f"{_fmt(ema[-1])}")
    grad = view.get("grad_norm") or []
    if grad:
        lines.append(f"|grad| {sparkline(grad, bar_w)}  "
                     f"{_fmt(grad[-1])}")
    rate_bits = [f"steps/s {_fmt(view.get('steps_per_sec'))}",
                 f"ETA {_fmt_eta(view.get('eta_s'))}"]
    comm = view.get("comm")
    if comm:
        rate_bits.append(
            f"comm {_fmt(comm.get('bytes_per_step'))} B/step")
    lines.append("  ".join(rate_bits))
    hmc = view.get("hmc")
    if hmc:
        div = hmc.get("divergences")
        if isinstance(div, list):
            div = sum(div)
        draws = hmc.get("step") or 0
        div_rate = (div / draws) if div is not None and draws else None
        lines.append(
            f"hmc  draw {draws}  accept={_fmt(_scalar(hmc.get('accept')))}"
            f"  divergences={_fmt(div)}"
            + (f" ({div_rate:.1%}/draw)" if div_rate is not None
               else ""))
    res = view.get("resources")
    if res:
        bits = [f"rss {_fmt_bytes(res.get('rss_bytes'))}"]
        busy = res.get("busy_frac")
        if busy is not None:
            bits.append(f"busy {busy:.0%}")
        if res.get("device_bytes_in_use") is not None:
            bits.append(
                f"dev {_fmt_bytes(res['device_bytes_in_use'])}")
        cc = res.get("compile_count")
        if cc is not None:
            bits.append(
                f"compiles {cc}"
                + (f" ({res['compile_s_total']:.1f}s)"
                   if res.get("compile_s_total") is not None
                   else ""))
        lines.append("res  " + "  ".join(bits))
    budgets = view.get("budgets")
    if budgets:
        bits = []
        for cls in sorted(budgets):
            b = budgets[cls]
            rem = b.get("remaining_frac")
            bit = (f"{cls} -" if rem is None
                   else f"{cls} {100.0 * rem:.0f}%")
            burn = b.get("burn_rate")
            if burn is not None:
                bit += f" b={burn:.1f}"
            if b.get("fast_burning"):
                bit += "!"
            bits.append(bit)
        lines.append("slo  " + "  ".join(bits))
    if view.get("stalled"):
        lines.append("STALL  no progress (heartbeat stall active)")
    summary = view.get("summary")
    if summary:
        final = _scalar(summary.get("final_loss"))
        if final is None and loss:
            final = loss[-1]     # scan fits: last tapped loss
        lines.append(
            f"done  final_loss={_fmt(final)}"
            + (f"  steps/s={_fmt(summary.get('steps_per_sec'))}"
               if summary.get("steps_per_sec") is not None else "")
            + (f"  postmortem={summary['postmortem_bundle']}"
               if summary.get("postmortem_bundle") else ""))
    for alert in (view.get("alerts") or [])[-4:]:
        lines.append(
            f"ALERT [{alert.get('rule')}] {alert.get('message', '')}"
            + (f" (step {alert.get('step')})"
               if alert.get("step") is not None else ""))
    if not (steps or loss or hmc or plan):
        lines.append("(no recognized telemetry records yet)")
    lines.append(f"records: {view.get('n_records', 0)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.dashboard",
        description="Streaming terminal dashboard over a telemetry "
                    "JSONL file.")
    parser.add_argument("path", help="telemetry .jsonl file (may "
                                     "still be growing)")
    parser.add_argument("--follow", action="store_true",
                        help="keep tailing and re-rendering until "
                             "interrupted")
    parser.add_argument("--once", action="store_true",
                        help="render one snapshot and exit "
                             "(deterministic; for tests/CI)")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="refresh period in seconds (--follow)")
    parser.add_argument("--width", type=int, default=64,
                        help="render width in columns")
    parser.add_argument("--max-frames", type=int, default=None,
                        help=argparse.SUPPRESS)   # test hook
    args = parser.parse_args(argv)

    reader = TailReader(args.path)
    records: list = []
    if args.once or not args.follow:
        if not os.path.exists(args.path):
            print(f"{args.path}: no such file", file=sys.stderr)
            return 1
        records += reader.poll()
        print(render(collect(records), width=args.width))
        return 0

    frames = 0
    collector = Collector()
    try:
        while True:
            # incremental: only this poll's NEW records are folded,
            # so a frame costs O(new records), not O(whole file)
            collector.feed(reader.poll())
            frame = render(collector.view(), width=args.width)
            # home + clear-to-end keeps the frame flicker-free on any
            # ANSI terminal; plain output when not a tty (piped logs).
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            else:
                sys.stdout.write(frame + "\n---\n")
            sys.stdout.flush()
            frames += 1
            if args.max_frames is not None \
                    and frames >= args.max_frames:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
