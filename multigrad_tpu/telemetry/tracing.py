"""Distributed request tracing: W3C-traceparent contexts + span sink.

The serve layer's end-to-end latency is a chain of hops nobody can
see from aggregate counters alone: router affinity routing, RPC
send (and its backoff retries), worker queue wait, bucket
coalescing, the compile-or-cached dispatch, the Adam scan itself,
finalize, the result's trip back — and, under preemption, whole
requeue odysseys across worker generations.  This module is the
context-propagation core that turns that chain into *one* navigable
waterfall per request:

* :class:`TraceContext` — a W3C-traceparent-style identity
  (``trace_id``, ``span_id``, ``parent_span_id``).  Minted once per
  request at :meth:`FleetRouter.submit <multigrad_tpu.serve.fleet
  .FleetRouter.submit>` (or :meth:`FitScheduler.submit
  <multigrad_tpu.serve.scheduler.FitScheduler.submit>` for
  single-process serving), serialized as a ``traceparent`` string
  (``00-<trace_id>-<span_id>-01``) on the ``submit`` wire message,
  and re-hydrated on the worker so every hop's span — on whichever
  process it happens — carries the same ``trace_id`` and a parent
  link back to the request's root span.
* :class:`Tracer` — the per-process span recorder: each finished hop
  becomes one ``trace_span`` JSONL record (``t_start``/``t_end``
  wall clock, ``elapsed_s``, ``ok``, free-form attributes), appended
  line-atomically through :class:`~multigrad_tpu.telemetry.metrics
  .JsonlSink` so per-process trace files are safe to tail and
  survive a SIGKILL with every already-written span intact — which
  is exactly what makes a killed worker's partial hops show up in
  the merged waterfall.

Merging is :func:`multigrad_tpu.telemetry.aggregate.merge_traces`
(group the per-process files' spans by ``trace_id``); rendering is
``python -m multigrad_tpu.telemetry.trace`` (stdlib-only — a trace
is debuggable from the JSONLs alone, no live process needed).

Wall-clock convention: span endpoints are ``time.time()`` on the
recording process.  Fleet workers today share the router's host, so
cross-process spans align directly; across hosts the per-hop
*durations* stay exact while offsets inherit clock skew (the
``multigrad_fleet_rpc_rtt`` gauge is the noise floor to read them
against).

This module is pure stdlib, per the telemetry package contract.
"""
from __future__ import annotations

import contextlib
import os
import secrets
import threading
import time
from dataclasses import dataclass
from typing import Optional

__all__ = ["TraceContext", "Tracer", "new_trace",
           "parse_traceparent", "TRACE_EVENT"]

#: Record type of one finished hop in a telemetry/trace JSONL stream.
TRACE_EVENT = "trace_span"

_TRACE_ID_LEN = 32        # 16 random bytes, hex
_SPAN_ID_LEN = 16         # 8 random bytes, hex


def _new_id(hex_len: int) -> str:
    return secrets.token_hex(hex_len // 2)


@dataclass(frozen=True)
class TraceContext:
    """One span's identity within a trace (W3C traceparent shape).

    ``trace_id`` names the whole request journey (32 hex chars);
    ``span_id`` names this span (16 hex chars); ``parent_span_id``
    links it into the waterfall (``None`` marks the root).  Contexts
    are immutable — :meth:`child` derives a new span under this one,
    which is how a hop's recorder parents itself without any shared
    mutable state across threads or processes.
    """

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None

    def child(self) -> "TraceContext":
        """A fresh span context parented under this span."""
        return TraceContext(self.trace_id, _new_id(_SPAN_ID_LEN),
                            self.span_id)

    @property
    def traceparent(self) -> str:
        """The W3C ``traceparent`` header rendering
        (``00-<trace_id>-<span_id>-01``).  The parent link is NOT in
        the header (per the spec): the receiver's spans parent to
        ``span_id``, which is the point of propagation."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_wire(self) -> dict:
        """The dict form carried on serve wire messages.  Receivers
        must treat the whole field as optional — mixed-version
        fleets have undecorated peers (see :func:`parse_traceparent`
        for the tolerant read side)."""
        return {"traceparent": self.traceparent}

    @classmethod
    def from_wire(cls, value) -> Optional["TraceContext"]:
        """Re-hydrate a context from a wire dict; ``None`` on
        anything malformed or absent (never raises — an undecorated
        or future-versioned peer must not kill the handler)."""
        if not isinstance(value, dict):
            return None
        return parse_traceparent(value.get("traceparent"))


def new_trace() -> TraceContext:
    """Mint a fresh root context: new ``trace_id``, new ``span_id``,
    no parent.  Called exactly once per request, at the submit
    surface the request first enters."""
    return TraceContext(_new_id(_TRACE_ID_LEN),
                        _new_id(_SPAN_ID_LEN), None)


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a ``traceparent`` string; ``None`` on malformed input.

    Deliberately tolerant (no exceptions): trace fields roll out
    across a mixed-version fleet, so a worker must shrug off a
    missing, truncated, or future-versioned header and serve the
    fit untraced rather than reject it.
    """
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != _TRACE_ID_LEN or len(span_id) != _SPAN_ID_LEN:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    return TraceContext(trace_id, span_id, None)


class Tracer:
    """Per-process span recorder writing ``trace_span`` records.

    Parameters
    ----------
    sink : str | sink | None
        A path (wrapped in a line-atomic :class:`~multigrad_tpu
        .telemetry.metrics.JsonlSink` — parent directory created),
        any object with ``write(record)``/``close()``, or ``None``
        for an in-memory ring (:class:`~multigrad_tpu.telemetry
        .metrics.MemorySink`) — the test/ad-hoc mode, readable via
        :attr:`records`.
    service : str, optional
        Stamped on every span (``"router"``, ``"worker:w0"``, ...)
        so a merged waterfall names which process ran each hop.

    Thread-safe: the fleet router's reader threads, the scheduler's
    dispatcher thread, and worker waiter threads all record
    concurrently.
    """

    def __init__(self, sink=None, service: Optional[str] = None):
        from .metrics import JsonlSink, MemorySink
        self.path = None
        if sink is None:
            sink = MemorySink(capacity=65536)
        elif isinstance(sink, str):
            parent = os.path.dirname(os.path.abspath(sink))
            os.makedirs(parent, exist_ok=True)
            self.path = sink
            sink = JsonlSink(sink)
        from .._lockdep import make_lock
        self._sink = sink
        self.service = service
        self._lock = make_lock("telemetry.tracing.Tracer._lock")
        self._closed = False

    # -- span production ----------------------------------------------------
    def new_trace(self) -> TraceContext:
        return new_trace()

    def record(self, ctx: TraceContext, name: str, t_start: float,
               t_end: Optional[float] = None, ok: bool = True,
               **attrs) -> dict:
        """Write one finished span.  ``t_start``/``t_end`` are wall
        clock (``time.time()``); attributes are free-form JSON-able
        fields (worker id, bucket size, retry counts, postmortem
        bundle paths...).  Returns the record written."""
        t_end = time.time() if t_end is None else float(t_end)
        t_start = float(t_start)
        record = {
            "event": TRACE_EVENT,
            "t": t_end,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_span_id": ctx.parent_span_id,
            "name": name,
            "service": self.service,
            "t_start": t_start,
            "t_end": t_end,
            "elapsed_s": max(0.0, t_end - t_start),
            "ok": bool(ok),
        }
        record.update(attrs)
        self._write(record)
        return record

    @contextlib.contextmanager
    def span(self, parent: TraceContext, name: str, **attrs):
        """Record a hop around a block; yields the child context so
        nested hops can parent under it.  A block that raises still
        records, with ``ok: false``."""
        ctx = parent.child()
        t0 = time.time()
        ok = True
        try:
            yield ctx
        except BaseException:
            ok = False
            raise
        finally:
            self.record(ctx, name, t0, time.time(), ok=ok, **attrs)

    def log(self, event: str, **fields) -> dict:
        """Write a non-span record into the trace stream (e.g. the
        router's ``trace_rtt`` noise-floor samples)."""
        record = {"event": event, "t": time.time(),
                  "service": self.service, **fields}
        self._write(record)
        return record

    def _write(self, record: dict):
        with self._lock:
            if self._closed:
                return
            # lock-ok: callback-under-lock the tracer's sinks are the line-atomic JsonlSink / MemorySink (tiny appends, no locks of their own); the lock totally orders spans per process, which the waterfall merge depends on
            self._sink.write(record)

    # -- read/lifecycle -----------------------------------------------------
    @property
    def records(self) -> list:
        """In-memory records (only for the ``sink=None`` mode)."""
        return getattr(self._sink, "records", [])

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
