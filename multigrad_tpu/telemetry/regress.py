"""Noise-aware bench-dossier regression gate.

::

    python -m multigrad_tpu.telemetry.regress BENCH_r05.json BENCH_r06.json
    python -m multigrad_tpu.telemetry.regress --pct 30 --floor-ms 100 r*.json
    python -m multigrad_tpu.telemetry.regress --tuned BENCH_r09.json

Compares bench dossier rounds (the ``BENCH_r{N}.json`` files
``bench.py`` emits — the incremental ``.bench_partial.<backend>.json``
files load too, they share the ``configs`` key) metric by metric,
renders the cross-round trajectory, and exits nonzero when the last
round regressed against its predecessor.  Built for the measurement
environment BENCH_NOTES §1 documents — a tunneled chip with a
3–70 ms per-call floor and ±20% session-to-session variance — where
naive ``new < old`` comparisons lie:

* **relative threshold** (``--pct``, default 25): a metric must move
  more than this fraction in its *worse* direction to count —
  BENCH_NOTES records ±20% honest session variance on the headline.
* **noise floor** (``--floor-ms``): time-type metrics (``*_s`` /
  ``*_ms`` — each one a per-evaluation measurement that pays the
  tunnel round trip) are additionally quiet while the absolute delta
  stays under the floor.  Default: 2× the larger ``tunnel_rtt_ms``
  recorded in the two dossiers being compared — the floor travels
  WITH the data, so a low-RTT session gets a tight gate and a noisy
  one a loose gate automatically.
* **direction inference**: ``*_per_sec`` / ``speedup`` /
  ``overlap_frac`` / ``min_ess`` are higher-better; ``*_s`` /
  ``*_ms`` / ``stall_fraction`` / ``max_rhat`` are lower-better;
  anything else (row counts, windows, booleans, provenance) is
  untracked — a new config never flakes the gate.
* **null handling**: a metric that is ``null`` in either round (an
  unmeasured config — most of BENCH_r05) is warn-only, never a
  failure; the gate only judges numbers against numbers.

Pure stdlib (the ``-m`` form still imports the package and jax; run
the file directly on a jax-less box).
"""
from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from typing import Optional

__all__ = ["load_dossier", "flatten_configs", "metric_direction",
           "is_time_metric", "time_delta_ms", "compare_rounds",
           "compare_tuned", "render_trajectory", "main"]

_HIGHER_SUFFIXES = ("per_sec", "speedup", "overlap_frac", "min_ess",
                    "iters_per_sec", "fairness_index",
                    "accuracy_frac")
_LOWER_SUFFIXES = ("_s", "_ms", "stall_fraction", "max_rhat")
# Names that match a direction suffix but are counters/bookkeeping,
# not performance targets.
_UNTRACKED = ("bytes", "chunks", "n_rows", "n_bins", "n_epochs",
              "nsteps", "records", "bin_window", "measured_at",
              "divergences", "nit", "nfev")


def load_dossier(path: str) -> dict:
    """One bench round: ``{"name", "configs", "tunnel_rtt_ms"}``.

    Accepts both the dossier JSON ``bench.py`` prints (``metric`` /
    ``value`` / ``configs`` / ``tunnel_rtt_ms``) and the incremental
    partial files (``configs`` / ``provenance``).  The headline
    ``value`` joins the metric table as ``headline``.
    """
    with open(path) as f:
        raw = json.load(f)
    if isinstance(raw, dict) and "configs" not in raw \
            and isinstance(raw.get("parsed"), dict):
        # The round driver's wrapper (BENCH_r05.json's shape): the
        # dossier proper rides under "parsed".
        raw = raw["parsed"]
    if not isinstance(raw, dict) or "configs" not in raw:
        raise ValueError(
            f"{path}: not a bench dossier (no 'configs' key)")
    configs = dict(raw["configs"])
    if isinstance(raw.get("value"), (int, float)):
        configs.setdefault("headline", raw["value"])
    return {
        "name": os.path.splitext(os.path.basename(path))[0],
        "path": path,
        "configs": flatten_configs(configs),
        "tunnel_rtt_ms": raw.get("tunnel_rtt_ms"),
    }


def flatten_configs(configs: dict, prefix: str = "") -> dict:
    """Numeric leaves of a nested config dict under dotted names
    (``galhalo_hist_fused_bins_ab.sigma005.speedup``).  ``None``
    leaves are kept (they mean "deliberately unmeasured")."""
    out: dict = {}
    for key, val in configs.items():
        name = f"{prefix}{key}"
        if isinstance(val, dict):
            out.update(flatten_configs(val, name + "."))
        elif val is None or (isinstance(val, (int, float))
                             and not isinstance(val, bool)):
            out[name] = val
    return out


def _leaf(name: str) -> str:
    """The direction-bearing tail of a dotted metric name, with the
    A/B backend tag stripped (``pair_1e5_fwdbwd_s_xla`` classifies
    by ``..._s``)."""
    leaf = name.rsplit(".", 1)[-1]
    for tag in ("_xla", "_pallas"):
        if leaf.endswith(tag):
            leaf = leaf[:-len(tag)]
    return leaf


def metric_direction(name: str) -> int:
    """+1 higher-better, -1 lower-better, 0 untracked."""
    leaf = _leaf(name)
    if leaf == "headline" or leaf.endswith(_HIGHER_SUFFIXES):
        return +1
    if any(tok in leaf for tok in _UNTRACKED):
        return 0
    if leaf.endswith(_LOWER_SUFFIXES):
        return -1
    return 0


def is_time_metric(name: str) -> bool:
    leaf = _leaf(name)
    return leaf.endswith("_s") or leaf.endswith("_ms")


def time_unit_scale_ms(name: str) -> float:
    """Multiplier taking a time metric's value to milliseconds."""
    return 1.0 if _leaf(name).endswith("_ms") else 1e3


def time_delta_ms(name: str, prev: float, cur: float) -> float:
    """Absolute delta of a time metric, in milliseconds."""
    return abs(cur - prev) * time_unit_scale_ms(name)


def _resolve_floor_ms(prev_round: dict, cur_round: dict,
                      floor_ms: Optional[float]) -> float:
    if floor_ms is not None:
        return float(floor_ms)
    rtts = [r.get("tunnel_rtt_ms") for r in (prev_round, cur_round)]
    rtts = [r for r in rtts if isinstance(r, (int, float))]
    # 2x the recorded floor: one dispatch's worth of noise on each
    # side of the comparison (BENCH_NOTES §1's per-call floor).
    return 2.0 * max(rtts) if rtts else 0.0


def compare_rounds(prev_round: dict, cur_round: dict,
                   pct: float = 25.0,
                   floor_ms: Optional[float] = None,
                   include=None) -> list:
    """Metric-by-metric judgment of ``cur`` against ``prev``.

    Returns one entry per metric: ``{"metric", "prev", "cur",
    "change_pct", "status"}`` with status in ``regressed`` /
    ``improved`` / ``ok`` (within thresholds) / ``noise-floor``
    (over pct but under the rtt-derived floor) / ``null`` (either
    side unmeasured — warn-only) / ``untracked``.
    """
    floor = _resolve_floor_ms(prev_round, cur_round, floor_ms)
    prev, cur = prev_round["configs"], cur_round["configs"]
    names = sorted(set(prev) | set(cur))
    if include:
        names = [n for n in names
                 if any(fnmatch.fnmatch(n, pat) for pat in include)]
    results = []
    for name in names:
        p, c = prev.get(name), cur.get(name)
        entry = {"metric": name, "prev": p, "cur": c,
                 "change_pct": None}
        direction = metric_direction(name)
        if direction == 0:
            entry["status"] = "untracked"
        elif not isinstance(p, (int, float)) \
                or not isinstance(c, (int, float)):
            entry["status"] = "null"
        elif p == 0:
            entry["status"] = "null"     # no meaningful ratio
        else:
            change = (c - p) / abs(p) * 100.0
            entry["change_pct"] = round(change, 2)
            worse = change * direction < 0
            beyond_pct = abs(change) > pct
            if not beyond_pct:
                entry["status"] = "ok"
            elif worse and is_time_metric(name) \
                    and time_delta_ms(name, p, c) <= floor:
                entry["status"] = "noise-floor"
            elif worse:
                entry["status"] = "regressed"
            else:
                entry["status"] = "improved"
        results.append(entry)
    return results


def compare_tuned(round_: dict, pct: float = 25.0,
                  floor_ms: Optional[float] = None) -> list:
    """Within-round autotuner gate: every ``*tuned*`` metric judged
    against its ``*handset*`` sibling.

    ``bench.py --tuned`` records tuner-resolved and hand-set-default
    legs side by side (``tune_*`` configs: ``tuned_s`` next to
    ``handset_s``, ``tuned_steps_per_sec`` next to
    ``handset_steps_per_sec``, ...).  This gate enforces the
    autotuner's core promise — **a tuner pick that is slower than the
    old hand-set default fails CI** — with the same pct/noise-floor
    tolerance the cross-round gate uses (direction inferred from the
    metric name as usual, so throughput pairs and time pairs both
    judge correctly).  Returns one entry per pair: ``{"metric",
    "handset", "tuned", "change_pct", "status"}`` with status
    ``regressed`` / ``improved`` / ``ok`` / ``noise-floor`` /
    ``null``.
    """
    floor = _resolve_floor_ms(round_, round_, floor_ms)
    configs = round_["configs"]
    results = []
    for name in sorted(configs):
        if "tuned" not in _leaf(name):
            continue
        # Sibling lookup swaps the token in the LEAF only — the
        # config container's name may itself contain "tuned"
        # (tuned_defaults.sigma005.tuned_s -> ....handset_s).
        head, _, leaf_raw = name.rpartition(".")
        base_name = (head + "." if head else "") \
            + leaf_raw.replace("tuned", "handset")
        if base_name == name or base_name not in configs:
            continue
        p, c = configs[base_name], configs[name]
        entry = {"metric": name, "handset": p, "tuned": c,
                 "change_pct": None}
        direction = metric_direction(name)
        if direction == 0:
            continue                       # bookkeeping pair
        if not isinstance(p, (int, float)) \
                or not isinstance(c, (int, float)) or p == 0:
            entry["status"] = "null"
        else:
            change = (c - p) / abs(p) * 100.0
            entry["change_pct"] = round(change, 2)
            worse = change * direction < 0
            beyond_pct = abs(change) > pct
            if not beyond_pct:
                entry["status"] = "ok"
            elif not worse:
                entry["status"] = "improved"
            elif is_time_metric(name) \
                    and time_delta_ms(name, p, c) <= floor:
                entry["status"] = "noise-floor"
            else:
                entry["status"] = "regressed"
        results.append(entry)
    return results


def render_trajectory(rounds: list, results: list) -> str:
    """The cross-round table: every tracked metric's value per round,
    with the last-pair judgment."""
    # Only judged metrics appear: compare_rounds already applied the
    # --include filter, so the table matches the gate's scope.
    judged = {r["metric"]: r for r in results}
    names = sorted(judged)
    headers = ["metric"] + [r["name"] for r in rounds] + ["Δ%", ""]
    rows = []
    for name in names:
        status = judged.get(name, {}).get("status", "")
        if status == "untracked":
            continue
        vals = []
        for rnd in rounds:
            v = rnd["configs"].get(name)
            vals.append("-" if not isinstance(v, (int, float))
                        else f"{v:.4g}")
        change = judged.get(name, {}).get("change_pct")
        mark = {"regressed": "<< REGRESSED", "improved": "improved",
                "noise-floor": "(noise floor)", "null": "(null)",
                "ok": ""}.get(status, "")
        rows.append([name] + vals
                    + ["-" if change is None else f"{change:+.1f}",
                       mark])
    widths = [max(len(str(row[i])) for row in [headers] + rows)
              for i in range(len(headers))]
    lines = ["  ".join(str(h).ljust(w)
                       for h, w in zip(headers, widths))]
    for row in rows:
        lines.append("  ".join(str(v).ljust(w)
                               for v, w in zip(row, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.regress",
        description="Noise-aware comparison of bench dossier rounds; "
                    "exits 1 when the last round regressed.")
    parser.add_argument("paths", nargs="+",
                        help="dossier JSONs, oldest first "
                             "(BENCH_r05.json BENCH_r06.json ...)")
    parser.add_argument("--pct", type=float, default=25.0,
                        help="relative worsening needed to flag "
                             "(default 25 — BENCH_NOTES records "
                             "±20%% session variance)")
    parser.add_argument("--floor-ms", type=float, default=None,
                        help="absolute noise floor for time metrics "
                             "(default: 2x the larger recorded "
                             "tunnel_rtt_ms)")
    parser.add_argument("--include", action="append", default=None,
                        metavar="GLOB",
                        help="restrict to metrics matching this "
                             "glob (repeatable)")
    parser.add_argument("--tuned", action="store_true",
                        help="also gate tuner-resolved configs "
                             "against their hand-set baselines "
                             "WITHIN the last round (the *tuned* / "
                             "*handset* metric pairs bench.py "
                             "--tuned records); a tuner pick slower "
                             "than the old default exits 1.  With "
                             "this flag a single dossier is enough")
    parser.add_argument("--warn-only", action="store_true",
                        help="report regressions but exit 0")
    parser.add_argument("--json", action="store_true",
                        help="emit the comparison as JSON")
    args = parser.parse_args(argv)
    if len(args.paths) < 2 and not args.tuned:
        parser.error("need at least two dossier rounds to compare "
                     "(or --tuned with one)")
    try:
        rounds = [load_dossier(p) for p in args.paths]
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    cross = len(rounds) >= 2
    results = compare_rounds(rounds[-2], rounds[-1], pct=args.pct,
                             floor_ms=args.floor_ms,
                             include=args.include) if cross else []
    tuned_results = compare_tuned(rounds[-1], pct=args.pct,
                                  floor_ms=args.floor_ms) \
        if args.tuned else []
    regressions = [r for r in results if r["status"] == "regressed"]
    tuned_regr = [r for r in tuned_results
                  if r["status"] == "regressed"]
    nulls = [r for r in results if r["status"] == "null"]
    if args.json:
        print(json.dumps({
            "rounds": [r["name"] for r in rounds],
            "pct": args.pct,
            "floor_ms": _resolve_floor_ms(rounds[-2] if cross
                                          else rounds[-1],
                                          rounds[-1],
                                          args.floor_ms),
            "results": results,
            "tuned": tuned_results,
            "regressions": len(regressions) + len(tuned_regr),
        }, indent=1))
    else:
        if cross:
            print(render_trajectory(rounds, results))
            floor = _resolve_floor_ms(rounds[-2], rounds[-1],
                                      args.floor_ms)
            print(f"\nthresholds: ±{args.pct:g}% relative, "
                  f"{floor:g} ms time-metric noise floor "
                  f"({rounds[-2]['name']} -> {rounds[-1]['name']})")
        for r in nulls:
            print(f"warn: {r['metric']} unmeasured in at least one "
                  f"round (prev={r['prev']}, cur={r['cur']})")
        for r in regressions:
            print(f"REGRESSION: {r['metric']} {r['prev']} -> "
                  f"{r['cur']} ({r['change_pct']:+.1f}%)")
        if cross and not regressions:
            print("no regressions beyond the noise thresholds")
        if args.tuned:
            print(f"\ntuned-vs-handset gate ({rounds[-1]['name']}):")
            for r in tuned_results:
                mark = {"regressed": "<< REGRESSED",
                        "noise-floor": "(noise floor)",
                        "null": "(null)"}.get(r["status"],
                                              r["status"])
                change = r["change_pct"]
                print(f"  {r['metric']}: handset={r['handset']} "
                      f"tuned={r['tuned']} "
                      + ("" if change is None else f"{change:+.1f}% ")
                      + mark)
            if not tuned_results:
                print("  (no tuned/handset metric pairs found)")
            elif not tuned_regr:
                print("  tuner-resolved configs within noise of "
                      "their hand-set baselines")
    if (regressions or tuned_regr) and not args.warn_only:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
