"""Wall-clock spans and liveness: the host-side half of telemetry.

:func:`span` is the one idiom that unifies the repo's scattered
timers — ``utils.profiling.Timer`` (benchmark reps),
``utils.profiling.trace`` (profiler capture), and
``StreamStats.summary()`` (prefetch counters) all measure *something
for some wall-clock window*; a span names the window, nests (a
``fit`` span contains ``checkpoint`` spans), and lands in the same
record stream as the in-graph taps, so one JSONL file tells the whole
story: when compilation ended, when each checkpoint was cut, what
fraction of the fit the stream spent stalled.

:class:`Heartbeat` is the liveness layer production pod training
treats as table stakes: a long streamed fit that stops ticking (a
wedged prefetch thread, a dead tunnel, a preempted host) is invisible
until a timeout kills the job — the heartbeat thread emits a
``heartbeat`` record every ``interval`` seconds with the last step it
saw, and a ``stall`` record the moment no progress has been observed
for ``stall_after`` seconds.  Every process emits (records carry
``process_index``), so under multi-host a single silent host is
identifiable from the surviving hosts' files.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

__all__ = ["span", "Heartbeat"]

_STACK = threading.local()


def _span_stack() -> list:
    stack = getattr(_STACK, "stack", None)
    if stack is None:
        stack = _STACK.stack = []
    return stack


@contextlib.contextmanager
def span(logger, name: str, trace=None, **fields):
    """Record a named wall-clock span around a block.

    Nesting is tracked per thread: a span opened inside another gets a
    ``path`` of ``"outer/inner"`` and ``depth`` of its nesting level,
    so the report can attribute child time to parents.  The record is
    written at span *exit* (elapsed is known then); spans that raise
    still record, with ``ok: false``.

    ``trace`` accepts a :class:`~multigrad_tpu.telemetry.tracing
    .TraceContext`: the span record is stamped with the trace's id
    and the context's span id as ``parent_span_id``, so wall-clock
    spans in a fit's telemetry stream correlate with the distributed
    request trace that triggered the fit (join on ``trace_id``).

    ``logger=None`` is a no-op context — callers can wire spans
    unconditionally and let the telemetry flag decide.
    """
    if logger is None:
        yield
        return
    if trace is not None:
        fields = {"trace_id": trace.trace_id,
                  "parent_span_id": trace.span_id, **fields}
    stack = _span_stack()
    path = "/".join([*stack, name])
    stack.append(name)
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        stack.pop()
        logger.log("span", name=name, path=path,
                   depth=len(stack), elapsed_s=time.perf_counter() - t0,
                   ok=ok, **fields)


class Heartbeat:
    """Background liveness emitter + stall detector for host loops.

    Parameters
    ----------
    logger : MetricsLogger
        Destination stream (``None`` disables everything — the same
        no-op convention as :func:`span`).
    interval : float
        Seconds between ``heartbeat`` records.
    stall_after : float, optional
        Emit a ``stall`` record when no :meth:`tick` has been seen for
        this many seconds (default ``3 * interval``).  One record per
        stall episode, plus a closing ``stall_recovered`` when ticks
        resume — not one per interval, so a long hang doesn't flood
        the stream.

    Usage::

        with Heartbeat(logger, interval=30.0) as hb:
            for step in range(nsteps):
                ...                      # one optimizer step
                hb.tick(step)
    """

    def __init__(self, logger, interval: float = 30.0,
                 stall_after: Optional[float] = None):
        self.logger = logger
        self.interval = float(interval)
        from .._lockdep import make_lock
        self.stall_after = (float(stall_after) if stall_after is not None
                            else 3.0 * float(interval))
        self._lock = make_lock("telemetry.spans.Heartbeat._lock")
        self._last_step: Optional[int] = None
        self._last_tick = time.perf_counter()
        self._prev_beat_step: Optional[int] = None
        self._prev_beat_time = time.perf_counter()
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- producer side (the fit loop) ---------------------------------------
    def tick(self, step: int):
        """Mark progress; call once per completed step."""
        with self._lock:
            self._last_step = int(step)
            self._last_tick = time.perf_counter()

    # -- heartbeat thread ---------------------------------------------------
    def _run(self):
        import jax

        process = jax.process_index()
        while not self._stop.wait(self.interval):
            now = time.perf_counter()
            with self._lock:
                step = self._last_step
                since_tick = now - self._last_tick
            rate = None
            if (step is not None and self._prev_beat_step is not None
                    and now > self._prev_beat_time):
                rate = ((step - self._prev_beat_step)
                        / (now - self._prev_beat_time))
            self.logger.log("heartbeat", step=step, process=process,
                            since_last_tick_s=round(since_tick, 3),
                            steps_per_sec=(round(rate, 3)
                                           if rate is not None else None))
            self._prev_beat_step, self._prev_beat_time = step, now
            if since_tick > self.stall_after and not self._stalled:
                self._stalled = True
                self.logger.log("stall", step=step, process=process,
                                stalled_s=round(since_tick, 3),
                                stall_after_s=self.stall_after)
            elif since_tick <= self.stall_after and self._stalled:
                self._stalled = False
                self.logger.log("stall_recovered", step=step,
                                process=process)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self.logger is not None and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mgt-heartbeat")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
