"""Live observability: in-process metrics registry + HTTP endpoint.

Everything telemetry did before this module is *offline*: JSONL files
read back by :mod:`.report` after the fact, postmortems dumped after a
fit died.  This module is the online half — the fleet-readable runtime
view pod-scale operations lean on to catch stragglers and divergence
while a job is still salvageable:

* :class:`LiveMetrics` — a tiny in-process registry of counters,
  gauges and histograms, rendered in the Prometheus text exposition
  format (version 0.0.4) so any standard scraper/agent can consume it.
* :class:`LiveSink` — the :class:`~multigrad_tpu.telemetry
  .MetricsLogger` **sink adapter**: give it to the logger (or pass
  ``live=`` to a fit entry point, which does it for you) and every
  record the fit emits is folded into the registry plus a rolling
  status view (current step, loss, steps/s, ETA from the fit plan,
  comm bytes/step, last-heartbeat age).
* :class:`LiveServer` — a daemon-thread stdlib ``http.server``
  exposing ``/metrics`` (Prometheus text), ``/status`` (JSON) and
  ``/healthz``.  It is itself a sink (it owns a :class:`LiveSink`),
  so ``live=LiveServer()`` is the whole wiring.

Multi-host: in-graph taps write on process 0 only, but spans,
heartbeats and stream counters are per-host facts — each process that
constructs a :class:`LiveServer` serves its *own* stream (a non-zero
``port`` is offset by ``jax.process_index()`` so hosts never
collide), and rank 0 can additionally serve the cross-rank fleet view
(``/fleet``) by pointing ``rank_paths=`` at the per-rank JSONL files;
the aggregation itself is :func:`multigrad_tpu.telemetry.aggregate
.aggregate` (merge, span skew, stragglers).

Wiring::

    from multigrad_tpu.telemetry import JsonlSink, LiveServer, MetricsLogger

    live = LiveServer(port=9100)          # port 0 = pick a free one
    log = MetricsLogger(JsonlSink("run.jsonl"))
    model.run_adam(guess, nsteps, telemetry=log, log_every=20,
                   live=live)
    # while the fit runs:
    #   curl localhost:9100/metrics   -> Prometheus exposition
    #   curl localhost:9100/status    -> {"step": ..., "eta_s": ...}

This module is stdlib-only at module level (jax is imported lazily
for process-index gating), per the telemetry package contract.
"""
from __future__ import annotations

import json
import re
import threading
import time
from typing import Optional, Sequence

__all__ = ["LiveMetrics", "LiveSink", "LiveServer",
           "LatencyObserver", "wire_monitoring"]

# Histogram bucket defaults: seconds-per-step on anything from a
# sub-ms CPU toy fit to a multi-second streamed pass.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                   10.0, 60.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _fmt_value(v) -> str:
    """Prometheus sample-value formatting (floats as %g, non-finite
    as the spec's NaN/+Inf/-Inf tokens)."""
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return f"{v:.10g}"


def _label_key(labels: Optional[dict]) -> str:
    """Deterministic `{k="v",...}` rendering (sorted; '' when None)."""
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace(
            '"', r"\"").replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


_LABEL_PAIR_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _parse_label_key(key: str) -> dict:
    """Inverse of :func:`_label_key` — recover the label dict from a
    rendered series key (counters/gauges store bare floats, so their
    labels survive only in the key)."""
    if not key:
        return {}
    return {k: v.replace(r"\n", "\n").replace(r"\"", '"')
               .replace("\\\\", "\\")
            for k, v in _LABEL_PAIR_RE.findall(key)}


class LiveMetrics:
    """Thread-safe counter/gauge/histogram registry.

    Names must match the Prometheus metric-name grammar
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``); an optional ``labels`` dict per
    sample keys independent series under one name.  A name's type is
    fixed by its first use — re-registering it as a different type
    raises (the exposition format forbids mixed types).
    """

    def __init__(self):
        from .._lockdep import make_lock
        self._lock = make_lock("telemetry.live.LiveMetrics._lock")
        self._metrics: dict = {}        # name -> metric dict

    def _metric(self, name: str, mtype: str, help: Optional[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        cur = self._metrics.get(name)
        if cur is None:
            cur = self._metrics[name] = {
                "type": mtype, "help": help or "", "samples": {}}
        elif cur["type"] != mtype:
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{cur['type']}, not {mtype}")
        elif help and not cur["help"]:
            cur["help"] = help
        return cur

    # -- write side ---------------------------------------------------------
    def inc(self, name: str, value: float = 1.0, help: str = None,
            labels: Optional[dict] = None):
        """Increment a counter (monotonic by contract)."""
        with self._lock:
            m = self._metric(name, "counter", help)
            key = _label_key(labels)
            m["samples"][key] = m["samples"].get(key, 0.0) + float(value)

    def set(self, name: str, value: float, help: str = None,
            labels: Optional[dict] = None, replace: bool = False):
        """Set a gauge to its current value.  ``replace=True`` drops
        the name's other label series first — for gauges whose label
        IS the payload (e.g. the slowest-fit exemplar gauge carries
        the offending ``trace_id`` as a label, and keeping every
        superseded trace's series would grow the exposition without
        bound)."""
        with self._lock:
            m = self._metric(name, "gauge", help)
            if replace:
                m["samples"].clear()
            m["samples"][_label_key(labels)] = float(value)

    def observe(self, name: str, value: float, help: str = None,
                buckets: Sequence[float] = DEFAULT_BUCKETS,
                labels: Optional[dict] = None,
                exemplar: Optional[str] = None):
        """Add one observation to a histogram (bucket edges are
        fixed by each label series' first observation).

        ``labels`` keys independent series under one name (the hop
        dimension of the serve-latency histograms); ``exemplar``
        attaches an identifier — a trace id — to the bucket the
        observation lands in (last write wins per bucket) and to the
        series maximum, so a tail-latency reading links straight to
        an offending trace (:meth:`exemplar`).  Exemplars surface
        through :meth:`snapshot`/:meth:`exemplar` and the ``/status``
        JSON, not the text exposition (0.0.4 predates OpenMetrics
        exemplar syntax).
        """
        with self._lock:
            m = self._metric(name, "histogram", help)
            key = _label_key(labels)
            h = m["samples"].get(key)
            if h is None:
                edges = tuple(sorted(float(b) for b in buckets))
                h = m["samples"][key] = {
                    "labels": dict(labels) if labels else None,
                    "buckets": edges,
                    "counts": [0] * len(edges),
                    "sum": 0.0, "count": 0,
                    "exemplars": {},
                }
            v = float(value)
            landed = None           # index of the bucket v falls in
            for i, edge in enumerate(h["buckets"]):
                if v <= edge:
                    h["counts"][i] += 1     # cumulative by contract
                    if landed is None:
                        landed = i
            if landed is None:
                landed = len(h["buckets"])      # +Inf overflow
            h["sum"] += v
            h["count"] += 1
            if v >= h.get("max", float("-inf")):
                h["max"] = v
                # An un-exemplared new maximum CLEARS the slot (the
                # field is documented as the worst observation's id;
                # a stale smaller observation's id must not pose as
                # it — exemplar() falls back to bucket exemplars).
                h["max_exemplar"] = (str(exemplar)
                                     if exemplar is not None
                                     else None)
            if exemplar is not None:
                h["exemplars"][landed] = str(exemplar)

    # -- read side ----------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able copy of the registry (tests, /status debugging)."""
        with self._lock:
            return json.loads(json.dumps(
                self._metrics, default=lambda o: list(o)))

    def quantile(self, name: str, q: float,
                 labels: Optional[dict] = None) -> Optional[float]:
        """Estimated q-quantile of a histogram series (linear
        interpolation inside the bucket the quantile falls in — the
        standard ``histogram_quantile`` estimate, clamped to the
        true observed maximum so the +Inf bucket never inflates a
        p99).  ``None`` for an absent or empty series."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m["type"] != "histogram":
                return None
            h = m["samples"].get(_label_key(labels))
            if h is None or not h["count"]:
                return None
            buckets = h["buckets"]
            counts = list(h["counts"])
            count = h["count"]
            vmax = h.get("max")
        target = float(q) * count
        prev_edge, prev_cum = 0.0, 0
        for edge, cum in zip(buckets, counts):
            if cum >= target:
                step = cum - prev_cum
                frac = 1.0 if step <= 0 else \
                    (target - prev_cum) / step
                est = prev_edge + frac * (edge - prev_edge)
                return min(est, vmax) if vmax is not None else est
            prev_edge, prev_cum = edge, cum
        # target lands in the +Inf overflow bucket
        return vmax if vmax is not None else buckets[-1]

    def exemplar(self, name: str,
                 labels: Optional[dict] = None) -> Optional[str]:
        """The exemplar attached to the slowest populated bucket of
        a histogram series — i.e. the trace id of (one of) the
        worst observations, the hook a tail-latency alarm follows
        straight into the waterfall."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m["type"] != "histogram":
                return None
            h = m["samples"].get(_label_key(labels))
            if h is None:
                return None
            if h.get("max_exemplar") is not None:
                return h["max_exemplar"]
            ex = h.get("exemplars") or {}
            return ex[max(ex)] if ex else None

    def histogram_stats(self, name: str,
                        labels: Optional[dict] = None
                        ) -> Optional[dict]:
        """``{count, sum, max}`` of a histogram series (``None`` if
        absent)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m["type"] != "histogram":
                return None
            h = m["samples"].get(_label_key(labels))
            if h is None:
                return None
            return {"count": h["count"], "sum": h["sum"],
                    "max": h.get("max")}

    def value(self, name: str,
              labels: Optional[dict] = None) -> Optional[float]:
        """Current value of a counter/gauge series (``None`` when
        the name or label series is absent, or the name is a
        histogram — use :meth:`quantile`/:meth:`histogram_stats`)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m["type"] == "histogram":
                return None
            v = m["samples"].get(_label_key(labels))
            return float(v) if v is not None else None

    def label_sets(self, name: str) -> list:
        """The label dicts a metric has series for (``{}`` for the
        unlabeled series) — how ``/status`` discovers which hops
        have latency histograms (and which tenants/classes the QoS
        counters track).  Histograms carry their label dicts;
        counter/gauge series are recovered from the rendered label
        key (exact inverse of :func:`_label_key` for the
        identifier-style label values this registry uses)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return []
            out = []
            for key, h in m["samples"].items():
                if isinstance(h, dict):
                    out.append(dict(h.get("labels") or {}))
                else:
                    out.append(_parse_label_key(key))
            return out

    def render(self) -> str:
        """The registry in Prometheus text exposition format 0.0.4."""
        with self._lock:
            lines = []
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m["help"]:
                    lines.append(f"# HELP {name} {m['help']}")
                lines.append(f"# TYPE {name} {m['type']}")
                if m["type"] == "histogram":
                    for key in sorted(m["samples"]):
                        h = m["samples"][key]
                        base = dict(h.get("labels") or {})
                        for edge, n in zip(h["buckets"],
                                           h["counts"]):
                            lk = _label_key(
                                {**base, "le": _fmt_value(edge)})
                            lines.append(f"{name}_bucket{lk} {n}")
                        lk = _label_key({**base, "le": "+Inf"})
                        lines.append(
                            f'{name}_bucket{lk} {h["count"]}')
                        lines.append(
                            f"{name}_sum{key} "
                            f"{_fmt_value(h['sum'])}")
                        lines.append(f"{name}_count{key} "
                                     f"{h['count']}")
                else:
                    for key, value in sorted(m["samples"].items()):
                        lines.append(f"{name}{key} {_fmt_value(value)}")
            return "\n".join(lines) + "\n"


class LatencyObserver:
    """Feed one serve layer's fit-latency histograms.

    The shared write side behind ``/status``'s ``latency`` section
    (:meth:`LiveSink.latency_summary`): end-to-end and per-hop
    observations land in ``<prefix>_fit_latency_seconds`` /
    ``<prefix>_hop_seconds{hop=...}`` with the trace id as the
    exemplar, and the slowest fit seen keeps a
    ``<prefix>_fit_latency_max_seconds`` gauge whose label IS the
    offending trace id.  The max latch is taken under a lock and the
    gauge is replaced inside it — the fleet router observes from one
    reader thread per worker, and an unsynchronized check-then-act
    would let a smaller concurrent latency clobber the true maximum's
    exemplar.

    ``metrics=None`` makes every call a no-op, so callers wire the
    observer unconditionally and let the ``live=`` flag decide.
    """

    def __init__(self, metrics: Optional[LiveMetrics],
                 prefix: str, noun: str):
        from .._lockdep import make_lock
        self.metrics = metrics
        self.prefix = prefix
        self.noun = noun
        # The max-latch gauge write happens inside the latch's
        # critical section (check-then-act on the maximum), an
        # ordering hidden behind the `self.metrics` indirection:
        # declared for the lockdep cross-check.
        self._lock = make_lock(
            "telemetry.live.LatencyObserver._lock",
            may_precede=("telemetry.live.LiveMetrics._lock",))
        self._max_s = 0.0

    def observe(self, e2e_s: float, hops: Optional[dict],
                trace_id: Optional[str]):
        m = self.metrics
        if m is None:
            return
        e2e_s = max(0.0, float(e2e_s))
        m.observe(f"{self.prefix}_fit_latency_seconds", e2e_s,
                  help=f"end-to-end {self.noun} latency "
                       "(submit -> result)",
                  exemplar=trace_id)
        for hop, v in (hops or {}).items():
            if isinstance(v, (int, float)):
                m.observe(f"{self.prefix}_hop_seconds", float(v),
                          help=f"{self.noun} latency by hop",
                          labels={"hop": hop}, exemplar=trace_id)
        if trace_id is None:
            return
        with self._lock:
            if e2e_s < self._max_s:
                return
            self._max_s = e2e_s
            m.set(f"{self.prefix}_fit_latency_max_seconds", e2e_s,
                  help=f"slowest {self.noun}; the offending trace "
                       "id is the label",
                  labels={"trace_id": trace_id}, replace=True)


class LiveSink:
    """The MetricsLogger sink adapter feeding a :class:`LiveMetrics`.

    Folds the record stream into the registry (prefix
    ``multigrad_``) and keeps the rolling :meth:`status` view the
    ``/status`` endpoint serves: current step, loss, steps/s over a
    trailing window of tap records, ETA against the fit plan
    (``fit_plan`` records carry ``nsteps`` — every wired fit driver
    emits one up front), comm bytes/step, last-heartbeat age, stall
    state and alert count.  Safe to reuse across fits: a new
    ``fit_plan`` (or ``run``) record resets the per-fit state.
    """

    def __init__(self, metrics: Optional[LiveMetrics] = None,
                 rate_window: int = 32):
        from .._lockdep import make_lock
        self.metrics = metrics or LiveMetrics()
        # Registry updates happen inside the fold's critical section
        # (the status view and the gauges must agree record-by-
        # record); the `self.metrics` indirection hides the edge
        # from the AST, so it is declared.
        self._lock = make_lock(
            "telemetry.live.LiveSink._lock",
            may_precede=("telemetry.live.LiveMetrics._lock",))
        self._rate_window = int(rate_window)
        self._run: Optional[dict] = None
        self._comm_bytes_per_step = None
        self._reset_fit()
        self._alerts = 0
        self._stalls = 0
        self._last_record_t: Optional[float] = None

    def _reset_fit(self):
        # NB: comm accounting deliberately survives a fit_plan — the
        # model drivers log it immediately BEFORE announcing the plan.
        self._plan: Optional[dict] = None
        self._ticks: list = []          # (t, step) of tap records
        self._step: Optional[int] = None
        self._loss = None
        self._grad_norm = None
        self._summary: Optional[dict] = None
        self._hmc: Optional[dict] = None
        # A fit aborted mid-stall must not leave the NEXT fit's
        # /status reporting stalled=true forever (the cumulative
        # _stalls counter survives; the episode flag does not).
        self._stalled = False
        self._last_heartbeat_t = None

    @staticmethod
    def _scalar(v):
        """First member of a batched tap value (report's convention)."""
        if isinstance(v, (list, tuple)):
            return float(v[0]) if v else None
        return float(v) if isinstance(v, (int, float)) else None

    # -- sink protocol ------------------------------------------------------
    def write(self, record: dict):
        event = record.get("event")
        t = record.get("t")
        m = self.metrics
        m.inc("multigrad_records_total", 1.0,
              help="telemetry records seen, by event",
              labels={"event": str(event)})
        with self._lock:
            self._last_record_t = t or time.time()
            if event == "run":
                self._run = dict(record)
                self._comm_bytes_per_step = None
                self._reset_fit()
            elif event == "fit_plan":
                self._reset_fit()
                self._plan = dict(record)
                if record.get("nsteps") is not None:
                    m.set("multigrad_nsteps", record["nsteps"],
                          help="planned steps of the current fit")
            elif event in ("adam", "hmc"):
                step = record.get("step")
                if step is not None and t is not None:
                    self._ticks.append((float(t), int(step)))
                    if len(self._ticks) > self._rate_window:
                        del self._ticks[0]
                    if len(self._ticks) >= 2:
                        (t0, s0), (t1, s1) = self._ticks[-2], \
                            self._ticks[-1]
                        if s1 > s0 and t1 > t0:
                            m.observe("multigrad_step_seconds",
                                      (t1 - t0) / (s1 - s0),
                                      help="wall seconds per step "
                                           "(from tap record spacing)")
                if step is not None:
                    self._step = int(step)
                    m.set("multigrad_step", step,
                          help="last step/draw seen from the fit")
                if event == "adam":
                    loss = self._scalar(record.get("loss"))
                    if loss is not None:
                        self._loss = loss
                        m.set("multigrad_loss", loss,
                              help="last tapped loss")
                    g = self._scalar(record.get("grad_norm"))
                    if g is not None:
                        self._grad_norm = g
                        m.set("multigrad_grad_norm", g,
                              help="last tapped |grad|")
                    for extra in ("loss_ema", "loss_ema_slope",
                                  "grad_noise_scale",
                                  "grad_norm_shard"):
                        v = self._scalar(record.get(extra))
                        if v is not None and v == v:
                            m.set(f"multigrad_{extra}", v)
                else:
                    self._hmc = {k: record.get(k) for k in
                                 ("step", "accept", "divergences",
                                  "step_size")}
                    a = self._scalar(record.get("accept"))
                    if a is not None:
                        m.set("multigrad_hmc_accept", a,
                              help="windowed HMC acceptance")
                    d = record.get("divergences")
                    if isinstance(d, (list, tuple)):
                        d = sum(d)
                    if isinstance(d, (int, float)):
                        m.set("multigrad_hmc_divergences", d,
                              help="cumulative HMC divergences")
            elif event == "comm":
                b = record.get("bytes_per_step")
                if b is not None:
                    self._comm_bytes_per_step = b
                    m.set("multigrad_comm_bytes_per_step", b,
                          help="collective payload per step")
            elif event == "heartbeat":
                self._last_heartbeat_t = t or time.time()
            elif event == "stall":
                self._stalls += 1
                self._stalled = True
                m.inc("multigrad_stalls_total",
                      help="heartbeat stall episodes")
            elif event == "stall_recovered":
                self._stalled = False
            elif event == "alert":
                self._alerts += 1
                m.inc("multigrad_alerts_total",
                      help="alert-rule firings, by rule",
                      labels={"rule": str(record.get("rule", "?"))})
            elif event == "bench":
                val = record.get("value")
                if isinstance(val, (int, float)) \
                        and not isinstance(val, bool):
                    m.set("multigrad_bench_value", val,
                          help="bench dossier config values",
                          labels={"config": str(record.get("config"))})
            elif event == "fit_summary":
                self._summary = dict(record)
                sps = record.get("steps_per_sec")
                if sps is not None:
                    m.set("multigrad_steps_per_sec", sps)
                fl = self._scalar(record.get("final_loss"))
                if fl is not None:
                    m.set("multigrad_loss", fl)

    def close(self):
        # Sinks attached per-fit outlive their logger by design: the
        # status/metrics view must stay scrapeable after the fit's
        # logger closes.  Nothing to release.
        pass

    # -- read side ----------------------------------------------------------
    def rate(self) -> Optional[float]:
        """Steps/s over the trailing tap-record window."""
        with self._lock:
            if len(self._ticks) < 2:
                return None
            (t0, s0), (t1, s1) = self._ticks[0], self._ticks[-1]
        if t1 <= t0 or s1 <= s0:
            return None
        return (s1 - s0) / (t1 - t0)

    def latency_summary(self) -> Optional[dict]:
        """Request-latency quantiles + exemplar traces for the
        ``/status`` ``latency`` section.

        Reads the serve layers' latency histograms out of the shared
        registry — ``multigrad_fleet_fit_latency_seconds`` (the
        router's end-to-end view, preferred) falling back to
        ``multigrad_serve_fit_latency_seconds`` (single-process
        scheduler) — and summarizes p50/p95/p99/max with the
        exemplar trace id of the slowest bucket, plus the same per
        hop (``*_hop_seconds{hop=...}``), so a tail-latency alarm
        links straight to the offending trace's waterfall.  ``None``
        when no fits have been served.
        """
        m = self.metrics
        for prefix in ("multigrad_fleet", "multigrad_serve"):
            name = f"{prefix}_fit_latency_seconds"
            stats = m.histogram_stats(name)
            if not stats or not stats["count"]:
                continue
            out = {
                "source": name,
                "count": stats["count"],
                "p50_s": m.quantile(name, 0.5),
                "p95_s": m.quantile(name, 0.95),
                "p99_s": m.quantile(name, 0.99),
                "max_s": stats["max"],
                "exemplar_trace": m.exemplar(name),
            }
            hop_name = f"{prefix}_hop_seconds"
            hops = {}
            for ls in m.label_sets(hop_name):
                hop = ls.get("hop")
                if hop is None:
                    continue
                hstats = m.histogram_stats(hop_name, labels=ls)
                hops[hop] = {
                    "count": hstats["count"],
                    "p50_s": m.quantile(hop_name, 0.5, labels=ls),
                    "p95_s": m.quantile(hop_name, 0.95,
                                        labels=ls),
                    "p99_s": m.quantile(hop_name, 0.99,
                                        labels=ls),
                    "max_s": hstats["max"],
                    "exemplar_trace": m.exemplar(hop_name,
                                                 labels=ls),
                }
            if hops:
                out["hops"] = hops
            return out
        return None

    def qos_summary(self) -> Optional[dict]:
        """Per-priority-class QoS health for the ``/status`` ``qos``
        section, recomputed from the shared registry on every scrape.

        Reads the ``multigrad_qos_*`` family the
        :class:`~multigrad_tpu.serve.slo.SloMonitor` exports: the
        per-class latency histograms
        (``multigrad_qos_fit_latency_seconds{priority_class=}``),
        the declared-SLO gauges (threshold + quantile), and the shed
        counters — and judges *measured vs declared* per class, so
        an operator (or the qos demo's receipt) can read a class's
        verdict from the endpoint alone.  ``None`` when no QoS
        metrics have landed (QoS off)."""
        m = self.metrics
        hist = "multigrad_qos_fit_latency_seconds"
        classes = sorted(
            ({ls.get("priority_class")
              for ls in m.label_sets(hist)} |
             {ls.get("priority_class")
              for ls in m.label_sets(
                  "multigrad_qos_slo_threshold_seconds")})
            - {None})
        if not classes:
            return None
        out: dict = {"classes": {}}
        for cls in classes:
            labels = {"priority_class": cls}
            stats = m.histogram_stats(hist, labels=labels) or {}
            entry: dict = {
                "count": stats.get("count", 0),
                "p50_s": m.quantile(hist, 0.5, labels=labels),
                "p95_s": m.quantile(hist, 0.95, labels=labels),
                "p99_s": m.quantile(hist, 0.99, labels=labels),
                "max_s": stats.get("max"),
                "exemplar_trace": m.exemplar(hist, labels=labels),
                "shed": int(m.value("multigrad_qos_shed_total",
                                    labels=labels) or 0),
            }
            threshold = m.value("multigrad_qos_slo_threshold_seconds",
                                labels=labels)
            if threshold is not None:
                q = m.value("multigrad_qos_slo_quantile",
                            labels=labels) or 0.95
                measured = m.quantile(hist, q, labels=labels)
                entry["slo"] = {
                    "threshold_s": threshold,
                    "quantile": q,
                    "measured_s": measured,
                    "ok": (None if measured is None
                           else bool(measured <= threshold)),
                }
            # Error-budget view (PR 20): the multigrad_slo_budget_*
            # gauges a SloBudget ledger exports — absent for classes
            # without a declared budget, so a pre-budget process's
            # qos section is unchanged.
            remaining = m.value(
                "multigrad_slo_budget_remaining_frac",
                labels=labels)
            if remaining is not None:
                burning = m.value(
                    "multigrad_slo_budget_fast_burning",
                    labels=labels)
                entry["budget"] = {
                    "remaining_frac": remaining,
                    "burn_rate": m.value(
                        "multigrad_slo_budget_burn_rate",
                        labels=labels),
                    "exhaustion_eta_s": m.value(
                        "multigrad_slo_budget_exhaustion_eta_s",
                        labels=labels),
                    "fast_burning": (bool(burning)
                                     if burning is not None
                                     else None),
                    "exemplar_trace": m.exemplar(
                        "multigrad_slo_budget_violation_seconds",
                        labels=labels),
                }
            out["classes"][cls] = entry
        shed_tenants = {
            ls["tenant"]: int(m.value(
                "multigrad_qos_shed_tenant_total", labels=ls) or 0)
            for ls in m.label_sets("multigrad_qos_shed_tenant_total")
            if ls.get("tenant")}
        if shed_tenants:
            out["shed_by_tenant"] = shed_tenants
        return out

    def resources_summary(self) -> Optional[dict]:
        """Process-resource health for the ``/status`` ``resources``
        section, read from the ``multigrad_resource_*`` gauges a
        :class:`~multigrad_tpu.telemetry.ResourceMonitor` exports.

        Also folds in the :func:`~multigrad_tpu.telemetry
        .autoscaler_inputs` contract (``busy_frac``, queue-wait p95,
        measured memory headroom) so the one documented place an
        autoscaler reads is the same endpoint operators look at.
        ``None`` when no monitor has exported (monitoring off) —
        the section stays off the JSON entirely, like ``qos``."""
        m = self.metrics
        if m.value("multigrad_resource_uptime_seconds") is None \
                and m.value("multigrad_resource_rss_bytes") is None:
            return None
        out = {
            "uptime_s": m.value("multigrad_resource_uptime_seconds"),
            "rss_bytes": m.value("multigrad_resource_rss_bytes"),
            "device_bytes_in_use": m.value(
                "multigrad_resource_device_bytes_in_use"),
            "device_peak_bytes": m.value(
                "multigrad_resource_device_peak_bytes"),
            "device_bytes_limit": m.value(
                "multigrad_resource_device_bytes_limit"),
            "busy_frac": m.value("multigrad_resource_busy_frac"),
            "busy_s_total": m.value(
                "multigrad_resource_busy_seconds_total"),
            "compile": {
                "count": m.value("multigrad_resource_compile_count"),
                "seconds_total": m.value(
                    "multigrad_resource_compile_seconds_total"),
                "cache_hits": m.value(
                    "multigrad_resource_compile_cache_hits"),
                "cache_misses": m.value(
                    "multigrad_resource_compile_cache_misses"),
            },
        }
        acc = m.value(
            "multigrad_resource_memory_model_accuracy_frac")
        if acc is not None:
            out["memory_model_accuracy_frac"] = acc
        # Serve-layer load context rides along when this process runs
        # a scheduler — the fleet-top's queue column reads it from
        # the same section instead of scraping /metrics.
        qd = m.value("multigrad_serve_queue_depth")
        if qd is not None:
            out["queue_depth"] = int(qd)
        fph = m.value("multigrad_serve_fits_per_hour")
        if fph is not None:
            out["fits_per_hour"] = fph
        from .resources import autoscaler_inputs
        out["autoscaler"] = autoscaler_inputs(m)
        # int-valued gauges come back as floats from the registry;
        # re-coerce byte/count fields so the JSON reads naturally.
        for key in ("rss_bytes", "device_bytes_in_use",
                    "device_peak_bytes", "device_bytes_limit"):
            if out[key] is not None:
                out[key] = int(out[key])
        for key in ("count", "cache_hits", "cache_misses"):
            if out["compile"][key] is not None:
                out["compile"][key] = int(out["compile"][key])
        return out

    def status(self, now: Optional[float] = None) -> dict:
        """The ``/status`` JSON: step/loss/steps-per-sec/ETA + liveness.

        ETA counts remaining planned steps (the ``fit_plan`` record's
        ``nsteps``, i.e. the segment schedule every driver announces
        up front) against the trailing steps/s.
        """
        now = time.time() if now is None else now
        rate = self.rate()
        with self._lock:
            done = self._summary is not None
            eta_s = None
            if (not done and rate and self._plan is not None
                    and self._plan.get("nsteps") is not None
                    and self._step is not None):
                remaining = max(
                    0, int(self._plan["nsteps"]) - 1 - self._step)
                eta_s = remaining / rate
            out = {
                "phase": ("done" if done else
                          "fitting" if self._step is not None else
                          "idle"),
                "step": self._step,
                "nsteps": (self._plan or {}).get("nsteps"),
                "fit_kind": (self._plan or {}).get("kind"),
                "loss": self._loss,
                "grad_norm": self._grad_norm,
                "steps_per_sec": rate,
                "eta_s": 0.0 if done else eta_s,
                "comm_bytes_per_step": self._comm_bytes_per_step,
                "last_record_age_s": (
                    round(now - self._last_record_t, 3)
                    if self._last_record_t else None),
                "last_heartbeat_age_s": (
                    round(now - self._last_heartbeat_t, 3)
                    if self._last_heartbeat_t else None),
                "stalled": self._stalled,
                "stalls": self._stalls,
                "alerts": self._alerts,
            }
            if self._hmc is not None:
                out["hmc"] = self._hmc
            if self._summary is not None:
                out["fit_summary"] = {
                    k: v for k, v in self._summary.items()
                    if k not in ("event", "t")}
            if self._run is not None:
                out["run"] = {k: self._run.get(k) for k in
                              ("backend", "device_kind", "device_count",
                               "process_index", "process_count",
                               "config_digest")}
        latency = self.latency_summary()
        if latency is not None:
            out["latency"] = latency
        qos = self.qos_summary()
        if qos is not None:
            out["qos"] = qos
        resources = self.resources_summary()
        if resources is not None:
            out["resources"] = resources
        # refresh derived gauges at read time (ages drift between
        # records; a scrape should see the current value)
        if out["last_heartbeat_age_s"] is not None:
            self.metrics.set("multigrad_heartbeat_age_seconds",
                             out["last_heartbeat_age_s"],
                             help="seconds since the last heartbeat")
        if out["steps_per_sec"] is not None:
            self.metrics.set("multigrad_steps_per_sec",
                             out["steps_per_sec"],
                             help="trailing-window fit rate")
        if out["eta_s"] is not None:
            self.metrics.set("multigrad_eta_seconds", out["eta_s"],
                             help="remaining planned steps / rate")
        return out


class LiveServer:
    """Daemon-thread HTTP endpoint over a :class:`LiveSink`.

    Also a sink itself (delegates to its :class:`LiveSink`), so the
    whole live stack wires as ``live=LiveServer()`` on any fit entry
    point — or explicitly as an extra sink of a
    :class:`~multigrad_tpu.telemetry.MetricsLogger`.

    Endpoints: ``/metrics`` (Prometheus text exposition 0.0.4),
    ``/status`` (JSON, see :meth:`LiveSink.status`), ``/healthz``
    (200 "ok"), and — when ``rank_paths`` names the per-rank JSONL
    files of a multi-host run — ``/fleet`` (the
    :func:`~multigrad_tpu.telemetry.aggregate.aggregate` summary:
    per-rank accounting, span skew, stragglers).

    ``port=0`` (default) binds a free ephemeral port (read it back
    from ``.port``/``.url``); a fixed nonzero port is offset by
    ``jax.process_index()`` so multi-host processes on one machine
    never collide.  Fleet workers are a third case the offset cannot
    cover — every worker is its own single-process jax runtime
    (``process_index() == 0``), so N workers sharing a host all
    resolve the same fixed port.  On ``EADDRINUSE`` the server
    therefore probes forward up to ``port_probe`` consecutive ports
    instead of crashing the worker at startup; the port actually
    bound is readable from ``.port`` and surfaced in the ``/status``
    JSON (``"port"``).  The serving thread is a daemon: it dies with
    the process, or earlier via :meth:`stop`.  ``close()`` (the sink
    protocol) deliberately does NOT stop the server — the endpoint
    outlives any single fit's logger.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 sink: Optional[LiveSink] = None,
                 rank_paths: Optional[Sequence[str]] = None,
                 port_probe: int = 16,
                 start: bool = True):
        self.sink = sink or LiveSink()
        self.metrics = self.sink.metrics
        self.rank_paths = list(rank_paths) if rank_paths else None
        if port:
            try:
                import jax
                port = int(port) + jax.process_index()
            except Exception:
                port = int(port)
        self._host = host
        self._port_requested = port
        self._port_probe = max(1, int(port_probe))
        self._httpd = None
        self._thread = None
        if start:
            self.start()

    # -- sink protocol (delegated) ------------------------------------------
    def write(self, record: dict):
        self.sink.write(record)

    def close(self):
        pass

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):    # silence per-request noise
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        server.sink.status()   # refresh derived gauges
                        self._send(
                            200, server.metrics.render().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/status":
                        status = server.sink.status()
                        # The bound port, not the requested one: with
                        # bind-retry active (fleet workers sharing a
                        # host) the two can differ, and operators
                        # resolve "which worker is this?" from here.
                        status["port"] = server.port
                        self._send(
                            200,
                            json.dumps(status, default=str).encode(),
                            "application/json")
                    elif path == "/healthz":
                        self._send(200, b"ok\n", "text/plain")
                    elif path == "/fleet" and server.rank_paths:
                        from .aggregate import aggregate
                        self._send(
                            200,
                            json.dumps(aggregate(server.rank_paths),
                                       default=str).encode(),
                            "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:        # client went away
                    pass
                except Exception as e:         # never kill the thread
                    try:
                        self._send(500, f"{e}\n".encode(), "text/plain")
                    except Exception:
                        pass

        # Fixed ports collide when several fleet workers share a host
        # (each is its own jax runtime, so the process_index offset
        # above is identically zero): probe forward a bounded range
        # on EADDRINUSE instead of crashing the worker at startup.
        # port=0 never probes — the OS hands out a free port.
        import errno
        probes = self._port_probe if self._port_requested else 1
        last_err = None
        for offset in range(probes):
            try:
                self._httpd = ThreadingHTTPServer(
                    (self._host,
                     self._port_requested + offset
                     if self._port_requested else 0), Handler)
                break
            except OSError as e:
                last_err = e
                if e.errno != errno.EADDRINUSE:
                    raise
        if self._httpd is None:
            raise OSError(
                errno.EADDRINUSE,
                f"no free port in [{self._port_requested}, "
                f"{self._port_requested + probes - 1}]") from last_err
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mgt-live-server")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self._host}:{self.port}" if self._httpd \
            else None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def wire_monitoring(telemetry, log_every: int, live=None, alerts=None,
                    default_log_every: int = 25):
    """Attach live/alert sinks to a fit's record stream.

    The shared plumbing behind every entry point's ``live=`` /
    ``alerts=`` parameters.  Returns ``(telemetry, log_every,
    owned)``:

    * with neither monitor: the arguments pass through untouched;
    * with a monitor and an existing logger: the monitors join it as
      extra sinks (idempotent — re-wiring at an inner driver is a
      no-op) and immediately receive the run record;
    * with a monitor but no logger: a fresh
      :class:`~multigrad_tpu.telemetry.MetricsLogger` over just the
      monitors is created and returned as ``owned`` — the caller must
      close it when the fit ends;
    * ``log_every`` is defaulted to ``default_log_every`` when unset,
      since a live view without tap records would be empty.

    Monitors exposing ``bind_logger`` (the
    :class:`~multigrad_tpu.telemetry.alerts.AlertEngine`, which emits
    ``alert`` records back into the stream) are bound to the logger.
    """
    monitors = [s for s in (live, alerts) if s is not None]
    if not monitors:
        return telemetry, log_every, None
    owned = None
    from .metrics import MetricsLogger
    if telemetry is None:
        telemetry = owned = MetricsLogger(*monitors)
    else:
        for s in monitors:
            telemetry.add_sink(s)
    for s in monitors:
        bind = getattr(s, "bind_logger", None)
        if bind is not None:
            bind(telemetry)
    if not log_every:
        log_every = default_log_every
    return telemetry, log_every, owned
