"""Metrics logging: pluggable sinks and run-record provenance.

The record stream is a flat sequence of JSON-able dicts, one per
event.  Every record carries ``event`` (its type) and ``t`` (wall
clock, ``time.time()``); everything else is event-specific.  The
stream's first record is always the **run record** — the provenance
header (jax/jaxlib versions, backend, device kind, mesh shape, config
digest) that makes a metrics file interpretable months later on a
different machine.  Event names in the shipped wiring:

========== =========================================================
``run``     provenance header (one per logger)
``adam``    in-graph optimizer tap (:mod:`.taps` via ``optim/adam``)
``hmc``     in-graph sampler tap (``inference/hmc``)
``comm``    collective-traffic accounting (:mod:`.comm`)
``stream``  :class:`~multigrad_tpu.utils.profiling.StreamStats` summary
``span``    nested wall-clock span (:mod:`.spans`)
``heartbeat``/``stall``  liveness records (:mod:`.spans`)
``fit_summary``  end-of-fit scalars (steps/s, final loss)
``trace_span``  one hop of a distributed request trace (:mod:`.tracing`)
``trace_rtt``  heartbeat-RPC round-trip sample (``serve/fleet``)
========== =========================================================

Sinks are deliberately tiny — ``write(record)`` + ``close()`` — so a
training service can add its own (a socket, a metrics agent) without
touching the callers.  This module imports only the standard library,
``numpy``, ``jax`` and the stdlib-only lockdep shadow
(:mod:`multigrad_tpu.utils.lockdep`); it must stay free of other
intra-package imports so every layer (collectives, optimizers,
models) can depend on it without cycles.
"""
from __future__ import annotations

import collections
import csv
import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from .._lockdep import make_rlock

__all__ = ["run_record", "config_digest", "JsonlSink", "CsvSink",
           "MemorySink", "MetricsLogger"]


def _jsonable(value):
    """Best-effort conversion of a record value to a JSON-able type."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, np.ndarray) or hasattr(value, "tolist"):
        return _jsonable(np.asarray(value).tolist())
    return str(value)


def config_digest(config) -> Optional[str]:
    """Short stable digest of a run configuration (sorted-key JSON →
    sha256 → 12 hex chars).  ``None`` config digests to ``None``."""
    if config is None:
        return None
    blob = json.dumps(_jsonable(config), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def run_record(config=None, **extra) -> dict:
    """The provenance header: what software/hardware produced a stream.

    Captures jax/jaxlib versions, the active backend, device kind and
    count, process topology, and a digest of ``config`` (the caller's
    run configuration — CLI args, bench config, fit hyperparameters).
    Safe to call before any device computation; it reads versions
    eagerly but touches devices only through ``jax.devices()``.
    """
    import jax
    import jaxlib

    try:
        devices = jax.devices()
        device_kind = devices[0].device_kind
        n_devices = len(devices)
        backend = jax.default_backend()
        proc_index, proc_count = jax.process_index(), jax.process_count()
    except RuntimeError:        # backend not initializable (rare)
        device_kind, n_devices, backend = None, 0, None
        proc_index, proc_count = 0, 1
    rec = {
        "event": "run",
        "t": time.time(),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "backend": backend,
        "device_kind": device_kind,
        "device_count": n_devices,
        "process_index": proc_index,
        "process_count": proc_count,
        "config_digest": config_digest(config),
    }
    if config is not None:
        rec["config"] = _jsonable(config)
    rec.update({k: _jsonable(v) for k, v in extra.items()})
    return rec


class JsonlSink:
    """Append records to a JSON-lines file, one record per line.

    The format every other telemetry consumer reads
    (:mod:`multigrad_tpu.telemetry.report`, the CI artifact): newline-
    delimited, self-describing, cat-able, resilient to truncation (a
    crash loses at most the last partial line).

    Writes are **line-atomic for live tails**: the file is opened
    unbuffered (binary) and each record lands as one ``write`` of a
    complete ``...\\n`` line, so a concurrent reader — the dashboard's
    ``--follow`` tail, a ``tail -f`` — can never observe a buffer
    flush splitting a record in half.  With ``fsync=True`` every
    record is additionally fsynced to disk — the durability knob for
    fits whose telemetry must survive a host power-cut (e.g. evidence
    streams feeding postmortems); leave it off for throughput.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = bool(fsync)
        # A writer that crashed mid-record leaves no trailing newline;
        # appending straight on would glue the next run's header onto
        # the truncated line, losing BOTH records.  Close the old line
        # first (the reader already skips unparseable lines).
        needs_newline = False
        try:
            with open(path, "rb") as f:
                f.seek(0, 2)
                if f.tell() > 0:
                    f.seek(-1, 2)
                    needs_newline = f.read(1) != b"\n"
        except OSError:
            pass
        self._f = open(path, "ab", buffering=0)
        if needs_newline:
            self._f.write(b"\n")

    def write(self, record: dict):
        line = json.dumps(_jsonable(record),
                          separators=(",", ":")) + "\n"
        self._f.write(line.encode())
        if self.fsync:
            os.fsync(self._f.fileno())

    def close(self):
        self._f.close()


class CsvSink:
    """Append records to a CSV file with a fixed column set.

    CSV cannot grow columns mid-stream, so the header is pinned at
    construction (``fields=``) or to the keys of the first record
    written; later records are projected onto it (missing fields write
    empty, extra fields are dropped).  Meant for single-event streams
    — e.g. a logger dedicated to ``adam`` tap records feeding a
    spreadsheet; use :class:`JsonlSink` for mixed streams.
    """

    def __init__(self, path: str, fields=None):
        self.path = path
        self._fields = list(fields) if fields is not None else None
        self._f = open(path, "a", newline="")
        self._writer = None

    def write(self, record: dict):
        if self._writer is None:
            if self._fields is None:
                self._fields = list(record)
            self._writer = csv.DictWriter(
                self._f, fieldnames=self._fields, extrasaction="ignore")
            if self._f.tell() == 0:
                self._writer.writeheader()
        self._writer.writerow(
            {k: _jsonable(record.get(k, "")) for k in self._fields})
        self._f.flush()

    def close(self):
        self._f.close()


class MemorySink:
    """In-memory ring buffer of the last ``capacity`` records.

    The zero-IO sink for tests and live dashboards: reading
    ``.records`` never blocks the writer for long (one lock-free-ish
    deque append per record, bounded memory by construction).
    """

    def __init__(self, capacity: int = 4096):
        self._buf = collections.deque(maxlen=capacity)

    @property
    def records(self) -> list:
        return list(self._buf)

    def write(self, record: dict):
        self._buf.append(dict(record))

    def close(self):
        pass


class MetricsLogger:
    """Fan a record stream out to one or more sinks.

    Parameters
    ----------
    *sinks
        Any objects with ``write(record)``/``close()``
        (:class:`JsonlSink`, :class:`CsvSink`, :class:`MemorySink`,
        or user-provided).  A convenience: a plain string argument is
        wrapped in a :class:`JsonlSink`.
    run_config : optional
        Configuration captured into the run record (see
        :func:`run_record`), written as the stream's first record.
    run_extra : dict, optional
        Extra provenance fields merged into the run record (e.g. the
        comm's mesh shape).

    Thread-safe: the in-graph taps' ``jax.debug.callback``\\ s, the
    prefetcher's loader thread, and the heartbeat thread may all log
    concurrently with the fit loop.
    """

    def __init__(self, *sinks, run_config=None, run_extra=None):
        self._sinks = [JsonlSink(s) if isinstance(s, str) else s
                       for s in sinks]
        # Re-entrant: a sink may emit back into its own stream from
        # inside write() — the AlertEngine logs `alert` records this
        # way — and a plain Lock would deadlock that same-thread
        # recursion.  Sinks are pluggable, so the lock-order edges
        # this opens cannot be derived statically: declared as a
        # fan-out source for the lockdep cross-check.
        self._lock = make_rlock(
            "telemetry.metrics.MetricsLogger._lock",
            may_precede="*")
        self._closed = False
        self.run = run_record(run_config, **(run_extra or {}))
        # Stamped on every record (not just the run header): multi-
        # host jobs write one JSONL per process, and merged streams
        # (telemetry.aggregate) are only attributable if each record
        # names its rank.
        self._process_index = self.run.get("process_index") or 0
        self._write(self.run)

    def add_sink(self, sink):
        """Attach another sink mid-stream (idempotent by identity).

        The hook behind the fit entry points' ``live=``/``alerts=``
        parameters: a monitor can join a logger the caller already
        constructed.  The new sink immediately receives the run
        record, so every sink sees a self-describing stream; a string
        is wrapped in a :class:`JsonlSink` like in the constructor.
        Returns the (possibly wrapped) sink.
        """
        if isinstance(sink, str):
            sink = JsonlSink(sink)
        with self._lock:
            if self._closed or any(s is sink for s in self._sinks):
                return sink
            self._sinks.append(sink)
            # lock-ok: callback-under-lock deliberate (PR 9): the lock is an RLock exactly so a sink may re-enter log() from inside write(); the replayed run record must be ordered before any record a racing log() would fan out
            sink.write(self.run)
        return sink

    def _write(self, record: dict):
        with self._lock:
            if self._closed:
                return
            for sink in self._sinks:
                # lock-ok: callback-under-lock deliberate (PR 9): sinks may re-enter (RLock) and the lock is what gives every sink the same total record order; the lock is declared may_precede="*" so lockdep still watches the edges sinks open
                sink.write(record)

    def log(self, event: str, **fields) -> dict:
        """Write one record; returns it (with ``event``/``t``/
        ``process_index`` stamped — explicit fields win)."""
        record = {"event": event, "t": time.time(),
                  "process_index": self._process_index, **fields}
        self._write(record)
        return record

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for sink in self._sinks:
                sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
