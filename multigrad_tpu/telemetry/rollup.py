"""Windowed telemetry history: the tiered rollup store.

Everything the stack exported before this module is either
*instantaneous* (the ``multigrad_resource_*`` gauges) or
*cumulative-since-process-start* (hop histograms, shed counters).
Neither can answer the questions the ROADMAP's elastic-fleet contract
actually asks — "is queue_wait p95 **rising**?", "has the device been
**sustainedly** idle?" — because both need a time axis.

:class:`RollupStore` is that axis: a bounded, tiered, windowed
time-series store, pure stdlib.  Samples land in fixed-width base
windows (default 10 s) and are simultaneously folded into coarser
tiers (default 1 m and 10 m); each tier keeps a fixed-size ring of
closed windows, so total memory is O(series × windows) forever —
retention is by construction, not by compaction jobs.  Per window the
store keeps ``count / sum / min / max / last`` plus (for sample
series) a capped, deterministically-decimated sample buffer, which is
what makes **windowed quantiles** possible where a cumulative
histogram can only ever answer "p95 since boot".

Feeding happens three ways, all concurrently safe:

* **direct** — :meth:`RollupStore.inc` / :meth:`~RollupStore.set` /
  :meth:`~RollupStore.observe` calls from instrumented code (the
  serve scheduler's settle path);
* **as a MetricsLogger sink** — :meth:`RollupStore.write` folds the
  record stream (``fit_summary`` → fits/queue-wait/per-tenant usage,
  ``resource_sample`` → busy-fraction gauge series), so
  ``logger.add_sink(store)`` gives any existing pipeline a history
  plane with zero call-site changes;
* **by scraping** — :meth:`RollupStore.attach_live` starts a daemon
  thread that periodically samples a :class:`~multigrad_tpu.telemetry
  .live.LiveMetrics` registry's gauges into gauge series and
  re-exports the windowed signals (`multigrad_rollup_*` gauges) back
  into the registry for ``/status`` and ``autoscaler_inputs`` v2.

Queries — :meth:`~RollupStore.delta`, :meth:`~RollupStore.rate`,
:meth:`~RollupStore.mean_over`, :meth:`~RollupStore.quantile_over`,
and :meth:`~RollupStore.trend` (least-squares slope with a
window-count floor) — pick the finest tier whose retention covers the
asked window.

Fleet history rides heartbeats: a worker calls
:meth:`~RollupStore.take_delta` to cut a compact since-last-heartbeat
delta (fixed known keys — see :data:`DELTA_KEYS`), ships it through
the ``rollup_to_wire``/``rollup_from_wire`` codecs in
:mod:`multigrad_tpu.serve.wire`, and the router folds it with
:meth:`~RollupStore.merge_delta` into fleet-level series that
**survive the worker** — a SIGKILL'd worker's already-shipped history
stays queryable at the router.

Pure stdlib at module level, per the telemetry package contract.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .._lockdep import make_lock

__all__ = [
    "RollupStore", "DELTA_KEYS",
    "FITS", "SHEDS", "DEVICE_BUSY_S", "QUEUE_WAIT_S", "BUSY_FRAC",
]

# ------------------------------------------------------------------ #
# canonical series names (the scheduler/router vocabulary — shared
# with serve/wire.py's heartbeat codec and the usage reporters)
# ------------------------------------------------------------------ #
#: Served-fit completions (counter).
FITS = "fits"
#: Class-aware queue sheds (counter).
SHEDS = "sheds"
#: Device-busy seconds from the dispatch duty-cycle bracket (counter).
DEVICE_BUSY_S = "device_busy_s"
#: Per-request queue-wait latency (sample series — windowed p95).
QUEUE_WAIT_S = "queue_wait_s"
#: Scraped instantaneous dispatch duty cycle (gauge series).
BUSY_FRAC = "busy_frac"

#: Fixed key set of a heartbeat rollup delta (:meth:`RollupStore
#: .take_delta`) — the known-keys contract ``serve/wire.py``'s
#: ``rollup_to_wire``/``rollup_from_wire`` codecs enforce.
DELTA_KEYS = ("t", "span_s", "fits", "sheds", "device_busy_s",
              "queue_wait_count", "queue_wait_sum_s",
              "queue_wait_max_s")

#: Default registry gauges the scrape loop samples into gauge series
#: (gauge name -> series name).
DEFAULT_SCRAPE = {
    "multigrad_resource_busy_frac": BUSY_FRAC,
    "multigrad_serve_queue_depth": "queue_depth",
    "multigrad_resource_rss_bytes": "rss_bytes",
}

_COUNTER, _GAUGE, _SAMPLE = "counter", "gauge", "sample"


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Exact linear-interpolation quantile over a sorted list (the
    same estimator :mod:`multigrad_tpu.serve.slo` uses, local copy so
    telemetry never imports serve)."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class _Window:
    """One fixed-width aggregation window."""

    __slots__ = ("start", "count", "sum", "min", "max", "last",
                 "samples")

    def __init__(self, start: float):
        self.start = start
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None
        self.samples: Optional[List[float]] = None

    def fold(self, value: float, keep_sample: bool,
             max_samples: int):
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self.last = value
        if keep_sample:
            if self.samples is None:
                self.samples = []
            self.samples.append(value)
            if len(self.samples) > max_samples:
                # Deterministic decimation (the SloMonitor buffer
                # idiom): drop every other sample, oldest-biased, so
                # the quantile stays representative under flood.
                del self.samples[::2]

    def fold_stats(self, count: int, total: float,
                   vmax: Optional[float]):
        """Fold a pre-aggregated contribution (a peer's heartbeat
        delta) — counts and sums merge exactly; samples are gone, so
        windowed quantiles on merged series degrade to mean/max."""
        self.count += int(count)
        self.sum += float(total)
        if vmax is not None:
            self.max = vmax if self.max is None \
                else max(self.max, vmax)
            if self.min is None:
                self.min = vmax


class _Series:
    """One named series: a ring of closed+current windows per tier,
    plus lifetime totals (what heartbeat deltas are cut from)."""

    __slots__ = ("kind", "tiers", "total_count", "total_sum",
                 "take_count", "take_sum", "take_max")

    def __init__(self, kind: str,
                 tiers: Tuple[Tuple[float, int], ...]):
        self.kind = kind
        # [(width_s, ring)] finest first; each ring holds _Windows.
        self.tiers = [(width, collections.deque(maxlen=keep))
                      for width, keep in tiers]
        self.total_count = 0
        self.total_sum = 0.0
        # since-last-take aggregates for heartbeat deltas
        self.take_count = 0
        self.take_sum = 0.0
        self.take_max: Optional[float] = None

    def _window(self, ring, width: float, t: float) -> _Window:
        start = (t // width) * width
        if ring and ring[-1].start == start:
            return ring[-1]
        w = _Window(start)
        ring.append(w)
        return w

    def fold(self, value: float, t: float, max_samples: int):
        keep = self.kind == _SAMPLE
        for i, (width, ring) in enumerate(self.tiers):
            # Samples only in the finest tier: coarser tiers answer
            # rate/trend questions, the fine tier answers quantiles,
            # and memory stays O(base windows × cap).
            self._window(ring, width, t).fold(
                value, keep and i == 0, max_samples)
        self.total_count += 1
        self.total_sum += value
        self.take_count += 1
        self.take_sum += value
        self.take_max = value if self.take_max is None \
            else max(self.take_max, value)

    def fold_stats(self, count: int, total: float,
                   vmax: Optional[float], t: float):
        for width, ring in self.tiers:
            self._window(ring, width, t).fold_stats(count, total,
                                                    vmax)
        self.total_count += int(count)
        self.total_sum += float(total)

    def windows_over(self, window_s: float,
                     now: float) -> List[_Window]:
        """Windows intersecting ``[now - window_s, now]`` from the
        finest tier whose retention covers the span."""
        for width, ring in self.tiers:
            if width * ring.maxlen >= window_s:
                break
        else:
            width, ring = self.tiers[-1]
        cutoff = now - window_s
        # a window intersects the span if it ends after the cutoff
        return [w for w in ring if w.start + width > cutoff]


class RollupStore:
    """Bounded tiered windowed time-series store (module docstring).

    Parameters
    ----------
    base_s : float
        Base window width in seconds.
    tiers : tuple of (width_s, keep)
        Window tiers, finest first; ``keep`` is the ring length per
        tier.  Defaults retain 15 min at 10 s, 1.5 h at 1 m, and 8 h
        at 10 m — enough for the 1 h/6 h slow burn-rate pair.
    max_samples : int
        Per-base-window sample cap for quantile series (decimated
        beyond it).
    max_series : int
        Hard cap on distinct series; further names are dropped
        silently (a misbehaving caller must not OOM the store).
    clock : callable
        Injected time source (tests drive a fake clock).
    """

    def __init__(self, base_s: float = 10.0,
                 tiers: Tuple[Tuple[float, int], ...] = (
                     (10.0, 90), (60.0, 90), (600.0, 48)),
                 max_samples: int = 512, max_series: int = 1024,
                 clock=time.time):
        if base_s is not None and (not tiers
                                   or tiers[0][0] != base_s):
            tiers = ((float(base_s), 90),) + tuple(
                t for t in tiers if t[0] != base_s)
        self.tiers = tuple((float(w), int(k)) for w, k in tiers)
        self.base_s = self.tiers[0][0]
        self.max_samples = int(max_samples)
        self.max_series = int(max_series)
        self._clock = clock
        self._series: Dict = {}
        self._lock = make_lock("telemetry.rollup.RollupStore._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._live = None
        self._scrape_names = dict(DEFAULT_SCRAPE)
        self._interval = 10.0
        self._closed = False
        self._last_take_t: Optional[float] = None

    # ---------------------------------------------------------- #
    # feeding: direct
    # ---------------------------------------------------------- #
    def _get(self, name, kind: str) -> Optional[_Series]:
        s = self._series.get(name)
        if s is None:
            if len(self._series) >= self.max_series:
                return None
            s = self._series[name] = _Series(kind, self.tiers)
        return s

    def inc(self, name, delta: float = 1.0,
            t: Optional[float] = None):
        """Counter increment: window value = increments landing in
        that window, so :meth:`delta`/:meth:`rate` come for free."""
        t = self._clock() if t is None else t
        with self._lock:
            s = self._get(name, _COUNTER)
            if s is not None:
                s.fold(float(delta), t, self.max_samples)

    def set(self, name, value: float, t: Optional[float] = None):
        """Gauge sample: the window keeps last/min/max/mean of the
        scraped values."""
        t = self._clock() if t is None else t
        with self._lock:
            s = self._get(name, _GAUGE)
            if s is not None:
                s.fold(float(value), t, self.max_samples)

    def observe(self, name, value: float,
                t: Optional[float] = None):
        """Latency-style sample: like :meth:`set` but the base tier
        additionally keeps (capped) raw samples for
        :meth:`quantile_over`."""
        t = self._clock() if t is None else t
        with self._lock:
            s = self._get(name, _SAMPLE)
            if s is not None:
                s.fold(float(value), t, self.max_samples)

    def merge_stats(self, name, count: int, total: float,
                    vmax: Optional[float] = None,
                    t: Optional[float] = None):
        """Fold a pre-aggregated contribution into the current
        window (the router's heartbeat-merge path)."""
        if count is None or total is None or count <= 0:
            return
        t = self._clock() if t is None else t
        with self._lock:
            s = self._get(name, _SAMPLE)
            if s is not None:
                s.fold_stats(count, total, vmax, t)

    # ---------------------------------------------------------- #
    # feeding: MetricsLogger sink protocol
    # ---------------------------------------------------------- #
    def write(self, record: dict):
        """Sink entry point: fold the record stream.  Unknown events
        count into per-event counters; ``fit_summary`` feeds the
        fit/queue-wait/usage series; ``resource_sample`` feeds the
        busy-fraction gauge.  Must never raise — a history store is
        not allowed to kill the fit."""
        try:
            event = record.get("event")
            if not isinstance(event, str) or event in (
                    "alert", "tenant_usage", "slo_budget"):
                return
            t = record.get("t")
            t = float(t) if isinstance(t, (int, float)) else None
            self.inc(("events", event), 1.0, t=t)
            if event == "fit_summary":
                self._fold_fit_summary(record, t)
            elif event == "resource_sample":
                bf = record.get("busy_frac")
                if isinstance(bf, (int, float)):
                    self.set(BUSY_FRAC, bf, t=t)
        except Exception:
            # Sink backstop: a malformed record drops on the floor;
            # the logger's other sinks still see it.
            pass

    def _fold_fit_summary(self, record: dict, t: Optional[float]):
        self.inc(FITS, 1.0, t=t)
        hops = record.get("hops")
        qw = hops.get("queue_wait") if isinstance(hops, dict) \
            else None
        if isinstance(qw, (int, float)):
            self.observe(QUEUE_WAIT_S, qw, t=t)
        # Per-request device-busy share: fit_s is the whole bucket's
        # device time; occupancy*bucket is the live-row count, so
        # fit_s/rows is this request's share and the series sums to
        # true device seconds (modulo padded rows, which belong to
        # nobody).
        fit_s = record.get("fit_s")
        occ = record.get("occupancy")
        bucket = record.get("bucket")
        share = None
        if isinstance(fit_s, (int, float)) \
                and isinstance(occ, (int, float)) \
                and isinstance(bucket, (int, float)) \
                and occ * bucket >= 1:
            share = float(fit_s) / max(1.0, round(occ * bucket))
            self.inc(DEVICE_BUSY_S, share, t=t)
        tenant = record.get("tenant")
        cls = record.get("priority_class")
        if isinstance(tenant, str) and isinstance(cls, str):
            self.note_usage(tenant, cls, fits=1,
                            busy_s=share or 0.0, t=t)

    def close(self):
        """Sink protocol + lifecycle: stop the scrape thread."""
        self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    # ---------------------------------------------------------- #
    # feeding: registry scrape loop
    # ---------------------------------------------------------- #
    def attach_live(self, live, interval_s: float = 10.0,
                    names: Optional[dict] = None) -> "RollupStore":
        """Start the scrape thread against a ``LiveMetrics``
        registry: every ``interval_s`` it samples the gauges in
        ``names`` (default :data:`DEFAULT_SCRAPE`) into gauge series
        and calls :meth:`export` to publish the windowed signals
        back.  Idempotent per store; :meth:`close` stops it."""
        self._live = live
        self._interval = float(interval_s)
        if names is not None:
            self._scrape_names = dict(names)
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._scrape_loop, daemon=True,
                name="mgt-rollup-scrape")
            self._thread.start()
        return self

    def _scrape_loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.scrape()
                self.export()
            except Exception:
                # Loop crash backstop: one bad scrape must not end
                # the history plane; the next tick retries.
                pass

    def scrape(self, live=None):
        """One scrape pass: sample the configured registry gauges
        into gauge series (values read OUTSIDE the store lock — the
        registry has its own)."""
        live = self._live if live is None else live
        if live is None:
            return
        t = self._clock()
        for gauge, series in self._scrape_names.items():
            v = live.value(gauge)
            if v is not None:
                self.set(series, v, t=t)

    def export(self, live=None, window_s: float = 300.0):
        """Publish the windowed autoscaler signals as
        ``multigrad_rollup_*`` gauges so ``/status`` and
        :func:`~multigrad_tpu.telemetry.resources.autoscaler_inputs`
        read them with no extra plumbing."""
        live = self._live if live is None else live
        if live is None:
            return
        p95 = self.quantile_over(QUEUE_WAIT_S, 0.95, window_s)
        if p95 is not None:
            live.set("multigrad_rollup_queue_wait_p95_s", p95,
                     help=f"windowed ({window_s:.0f}s) queue-wait "
                          "p95 from the rollup store")
        slope = self.trend(QUEUE_WAIT_S, window_s)
        if slope is not None:
            live.set("multigrad_rollup_queue_wait_trend", slope,
                     help="least-squares queue-wait slope (s/s) "
                          "over the rollup window")
        busy = self.mean_over(BUSY_FRAC, window_s)
        if busy is not None:
            live.set("multigrad_rollup_busy_frac_sustained", busy,
                     help="windowed mean dispatch duty cycle")

    # ---------------------------------------------------------- #
    # queries
    # ---------------------------------------------------------- #
    def _windows(self, name, window_s: float,
                 now: Optional[float]) -> List[_Window]:
        now = self._clock() if now is None else now
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            return list(s.windows_over(float(window_s), now))

    def delta(self, name, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Sum of a counter's increments over the trailing window
        (``None`` when no window has data)."""
        wins = self._windows(name, window_s, now)
        if not wins:
            return None
        return sum(w.sum for w in wins)

    def rate(self, name, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed increment rate per second."""
        d = self.delta(name, window_s, now)
        return None if d is None else d / float(window_s)

    def mean_over(self, name, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """Count-weighted mean of a series' values over the window —
        the ``busy_frac_sustained`` estimator."""
        wins = self._windows(name, window_s, now)
        count = sum(w.count for w in wins)
        if count <= 0:
            return None
        return sum(w.sum for w in wins) / count

    def max_over(self, name, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        wins = [w for w in self._windows(name, window_s, now)
                if w.max is not None]
        if not wins:
            return None
        return max(w.max for w in wins)

    def quantile_over(self, name, q: float, window_s: float,
                      now: Optional[float] = None
                      ) -> Optional[float]:
        """Exact (interpolated) quantile over the raw samples kept in
        the trailing window — the per-window p95 a cumulative
        histogram cannot produce.  ``None`` when the window holds no
        samples (including merged-stats-only fleet series)."""
        now = self._clock() if now is None else now
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            width, ring = s.tiers[0]
            cutoff = now - float(window_s)
            samples: List[float] = []
            for w in ring:
                if w.start + width > cutoff and w.samples:
                    samples.extend(w.samples)
        if not samples:
            return None
        samples.sort()
        return _quantile(samples, float(q))

    def trend(self, name, window_s: float,
              min_windows: int = 4,
              now: Optional[float] = None) -> Optional[float]:
        """Least-squares slope (value units per second) of per-window
        means over the trailing window.  ``None`` below the
        ``min_windows`` floor — two noisy points are not a trend."""
        wins = [w for w in self._windows(name, window_s, now)
                if w.count > 0]
        if len(wins) < max(2, int(min_windows)):
            return None
        xs = [w.start for w in wins]
        ys = [w.sum / w.count for w in wins]
        n = len(xs)
        mx = sum(xs) / n
        my = sum(ys) / n
        denom = sum((x - mx) ** 2 for x in xs)
        if denom <= 0.0:
            return None
        return sum((x - mx) * (y - my)
                   for x, y in zip(xs, ys)) / denom

    def names(self) -> list:
        with self._lock:
            return list(self._series)

    # ---------------------------------------------------------- #
    # heartbeat deltas + fleet merge
    # ---------------------------------------------------------- #
    def take_delta(self, now: Optional[float] = None
                   ) -> Optional[dict]:
        """Cut the compact since-last-take delta a worker ships on
        its heartbeat: the :data:`DELTA_KEYS` dict, or ``None`` when
        nothing happened (the heartbeat key stays off the wire, a
        legacy router sees the old protocol verbatim).  Resets the
        take cursors."""
        now = self._clock() if now is None else now
        with self._lock:
            span = (now - self._last_take_t
                    if self._last_take_t is not None else None)
            self._last_take_t = now
            out = {"t": now, "span_s": span}
            any_data = False
            for key, name in ((FITS, FITS), (SHEDS, SHEDS),
                              (DEVICE_BUSY_S, DEVICE_BUSY_S)):
                s = self._series.get(name)
                v = s.take_sum if s is not None else 0.0
                out[key] = v
                any_data = any_data or v > 0
                if s is not None:
                    s.take_count = 0
                    s.take_sum = 0.0
                    s.take_max = None
            s = self._series.get(QUEUE_WAIT_S)
            if s is not None and s.take_count > 0:
                out["queue_wait_count"] = s.take_count
                out["queue_wait_sum_s"] = s.take_sum
                out["queue_wait_max_s"] = s.take_max
                s.take_count = 0
                s.take_sum = 0.0
                s.take_max = None
                any_data = True
            else:
                out["queue_wait_count"] = 0
                out["queue_wait_sum_s"] = 0.0
                out["queue_wait_max_s"] = None
        if not any_data:
            return None
        out["fits"] = int(out["fits"])
        out["sheds"] = int(out["sheds"])
        return out

    def merge_delta(self, delta: dict, worker: Optional[str] = None,
                    prefix: str = "fleet."):
        """Fold a peer's heartbeat delta (a :meth:`take_delta` /
        ``rollup_from_wire`` dict) into fleet-level series.  The
        contribution is timestamped *now* at the merger — worker
        clocks never steer the router's windows — and persists after
        the worker dies, which is the whole point."""
        if not isinstance(delta, dict):
            return
        t = self._clock()
        for key in (FITS, SHEDS, DEVICE_BUSY_S):
            v = delta.get(key)
            if isinstance(v, (int, float)) and v > 0:
                self.inc(prefix + key, v, t=t)
                if worker is not None and key == FITS:
                    self.inc(("worker_fits", worker), v, t=t)
        self.merge_stats(prefix + QUEUE_WAIT_S,
                         delta.get("queue_wait_count"),
                         delta.get("queue_wait_sum_s"),
                         delta.get("queue_wait_max_s"), t=t)

    # ---------------------------------------------------------- #
    # per-tenant usage accounting
    # ---------------------------------------------------------- #
    def note_usage(self, tenant: str, priority_class: str,
                   fits: int = 0, busy_s: float = 0.0,
                   sheds: int = 0, violations: int = 0,
                   t: Optional[float] = None):
        """Account usage to a ``(tenant, priority_class)`` pair —
        the rollup series behind ``tenant_usage`` records, the
        report's ``usage:`` section and ``telemetry.top
        --tenants``."""
        key = (tenant, priority_class)
        if fits:
            self.inc(("tenant_fits",) + key, fits, t=t)
        if busy_s:
            self.inc(("tenant_busy_s",) + key, busy_s, t=t)
        if sheds:
            self.inc(("tenant_sheds",) + key, sheds, t=t)
        if violations:
            self.inc(("tenant_viol",) + key, violations, t=t)

    def usage_records(self, window_s: float = 600.0,
                      now: Optional[float] = None) -> List[dict]:
        """One ``tenant_usage`` record dict per (tenant, class) pair:
        lifetime totals plus the trailing-window fit count, ready for
        ``telemetry.log("tenant_usage", **rec)``."""
        now = self._clock() if now is None else now
        with self._lock:
            pairs = sorted({name[1:] for name in self._series
                            if isinstance(name, tuple)
                            and name[0] in ("tenant_fits",
                                            "tenant_busy_s",
                                            "tenant_sheds",
                                            "tenant_viol")})

            def total(kind, pair):
                s = self._series.get((kind,) + pair)
                return s.total_sum if s is not None else 0.0

            out = []
            for pair in pairs:
                tenant, cls = pair
                out.append({
                    "tenant": tenant, "priority_class": cls,
                    "fits": int(total("tenant_fits", pair)),
                    "busy_s": round(total("tenant_busy_s", pair), 6),
                    "sheds": int(total("tenant_sheds", pair)),
                    "violations": int(total("tenant_viol", pair)),
                    "window_s": float(window_s),
                })
        for rec in out:
            pair = (rec["tenant"], rec["priority_class"])
            d = self.delta(("tenant_fits",) + pair, window_s,
                           now=now)
            rec["fits_windowed"] = int(d) if d is not None else 0
        return out
