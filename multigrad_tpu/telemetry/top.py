"""``top`` for the fit fleet: live per-worker resource columns.

Usage::

    python -m multigrad_tpu.telemetry.top --once \\
        http://127.0.0.1:9100/status http://127.0.0.1:9101/status
    python -m multigrad_tpu.telemetry.top --follow w0.jsonl w1.jsonl

Each source is either a ``/status`` URL (a worker's or scheduler's
:class:`~multigrad_tpu.telemetry.LiveServer` — the ``resources``
section is the row) or a telemetry ``.jsonl`` path (the
``resource_sample`` records a :class:`~multigrad_tpu.telemetry
.ResourceMonitor` emits are folded, newest wins).  A URL or
single-line JSON file whose body carries a ``workers`` mapping (a
:attr:`FleetRouter.stats <multigrad_tpu.serve.fleet.FleetRouter
.stats>` snapshot) expands into one row per worker, so pointing top
at the router shows the whole fleet from one source.

Columns: window duty cycle (``BUSY%``), host RSS, device memory
in-use / limit and peak, compile count + cumulative seconds, queue
depth, trailing fits/hour, the worst-class SLO error budget
(remaining %% and burn rate, ``!`` while fast-burning — from the
``qos`` section of a ``/status`` body or folded ``slo_budget``
records), and sample age.  ``-`` means "source doesn't know"
(e.g. device columns on CPU backends, the SLO column on sources
with no declared SLOs) — never zero.

``--once`` prints a single deterministic table (CI receipts, tests);
``--follow`` redraws every ``--interval`` seconds; ``--json`` emits
the rows as a JSON list instead of the table (scripting);
``--tenants`` switches to per-(tenant, priority class) usage rows
folded from ``tenant_usage`` records (or a ``usage`` mapping in a
status body) — who burned the fleet, not which host is busy.

Pure stdlib — usable on a machine with nothing installed, same as
:mod:`.dashboard`.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from .dashboard import TailReader, _fmt_bytes

__all__ = ["fetch_source", "fold_records", "collect_rows",
           "render_rows", "collect_tenant_rows",
           "render_tenant_rows", "main"]

COLUMNS = ("WORKER", "BUSY%", "RSS", "DEV MEM", "PEAK",
           "COMPILE", "QUEUE", "FITS/H", "SLO", "AGE")

TENANT_COLUMNS = ("TENANT/CLASS", "FITS", "BUSY S", "SHED", "VIOL")


def _fmt_pct(frac) -> str:
    return "-" if frac is None else f"{100.0 * frac:5.1f}"


def _fmt_age(s) -> str:
    if s is None:
        return "-"
    return f"{s:.0f}s" if s < 120 else f"{s / 60.0:.0f}m"


def _fmt_slo(budgets) -> str:
    """Worst-class error-budget cell from a ``{class: budget-dict}``
    mapping: remaining percent and burn rate, ``!`` while
    fast-burning, ``-`` when no class is monitored."""
    worst = None
    for b in (budgets or {}).values():
        if not isinstance(b, dict) or b.get("remaining_frac") is None:
            continue
        if worst is None or b["remaining_frac"] < worst["remaining_frac"]:
            worst = b
    if worst is None:
        return "-"
    cell = f"{100.0 * worst['remaining_frac']:.0f}%"
    if worst.get("burn_rate") is not None:
        cell += f" b={worst['burn_rate']:.1f}"
    if worst.get("fast_burning"):
        cell += "!"
    return cell


def _status_budgets(st: dict) -> dict:
    """``{class: budget-dict}`` out of a status body's ``qos``
    section (:func:`~multigrad_tpu.telemetry.live.LiveMetrics
    .qos_summary` shape)."""
    qos = st.get("qos")
    out = {}
    if isinstance(qos, dict):
        for cls, entry in (qos.get("classes") or {}).items():
            if (isinstance(entry, dict)
                    and isinstance(entry.get("budget"), dict)):
                out[cls] = entry["budget"]
    return out


def _row(name, *, busy_frac=None, rss_bytes=None, dev_in_use=None,
         dev_limit=None, dev_peak=None, compile_count=None,
         compile_s=None, queue_depth=None, fits_per_hour=None,
         slo="-", age_s=None, state=None) -> dict:
    return {"name": str(name), "busy_frac": busy_frac,
            "rss_bytes": rss_bytes, "dev_in_use": dev_in_use,
            "dev_limit": dev_limit, "dev_peak": dev_peak,
            "compile_count": compile_count, "compile_s": compile_s,
            "queue_depth": queue_depth,
            "fits_per_hour": fits_per_hour, "slo": slo,
            "age_s": age_s, "state": state}


def _rows_from_status(name: str, st: dict, now: float) -> list:
    """Rows from one ``/status`` JSON body (or any dict shaped like
    it).  A ``workers`` mapping (router stats snapshot) expands to
    one row per worker; otherwise the ``resources`` section is the
    single row.  The SLO budget lives at the source (scheduler /
    router) level, so every expanded worker row carries the same
    worst-class cell."""
    slo = _fmt_slo(_status_budgets(st))
    workers = st.get("workers")
    if isinstance(workers, dict):
        rows = []
        for wid in sorted(workers):
            w = workers[wid] or {}
            res = w.get("resources") or {}
            rows.append(_row(
                wid,
                busy_frac=res.get("busy_frac"),
                rss_bytes=res.get("rss_bytes"),
                dev_in_use=res.get("device_bytes_in_use"),
                dev_limit=res.get("device_bytes_limit"),
                dev_peak=res.get("device_peak_bytes"),
                compile_count=res.get("compile_count"),
                compile_s=res.get("compile_s_total"),
                queue_depth=w.get("queue_depth"),
                slo=slo,
                age_s=w.get("heartbeat_age_s"),
                state=w.get("state")))
        return rows
    res = st.get("resources")
    if not isinstance(res, dict):
        return [_row(name, slo=slo)]
    compile_ = res.get("compile") or {}
    t = res.get("t")
    return [_row(
        name,
        busy_frac=res.get("busy_frac"),
        rss_bytes=res.get("rss_bytes"),
        dev_in_use=res.get("device_bytes_in_use"),
        dev_limit=res.get("device_bytes_limit"),
        dev_peak=res.get("device_peak_bytes"),
        compile_count=(compile_.get("count")
                       if compile_ else res.get("compile_count")),
        compile_s=(compile_.get("seconds_total")
                   if compile_ else res.get("compile_s_total")),
        queue_depth=res.get("queue_depth"),
        fits_per_hour=res.get("fits_per_hour"),
        slo=slo,
        age_s=(round(now - t, 1) if isinstance(t, (int, float))
               else None),
        state=st.get("phase"))]


def fold_records(state: dict, records: list):
    """Fold new telemetry records into a per-source state dict
    (newest ``resource_sample`` wins; a ``workers`` mapping — a
    router stats snapshot written as one JSONL line — replaces the
    whole state)."""
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("workers"), dict):
            state.clear()
            state["stats"] = rec
        elif rec.get("event") == "resource_sample":
            state["sample"] = rec
        elif rec.get("event") == "serve_dispatch":
            state["dispatches"] = state.get("dispatches", 0) + 1
        elif rec.get("event") == "slo_budget":
            cls = rec.get("priority_class")
            if isinstance(cls, str):
                state.setdefault("budgets", {})[cls] = rec
        elif rec.get("event") == "tenant_usage":
            key = f"{rec.get('tenant')}/{rec.get('priority_class')}"
            state.setdefault("usage", {})[key] = rec


def fetch_source(url: str, timeout: float = 2.0):
    """One ``/status`` fetch → parsed JSON dict, or ``None`` on any
    network/parse failure (a dead worker is a ``-`` row, not a
    crash of the whole top)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:
        return None


def collect_rows(sources: list, readers: dict, states: dict,
                 now=None) -> list:
    """One poll over all sources → the table's row dicts."""
    now = time.time() if now is None else now
    rows = []
    for src in sources:
        if src.startswith(("http://", "https://")):
            st = fetch_source(src)
            name = src.split("//", 1)[-1].split("/", 1)[0]
            if st is None:
                rows.append(_row(name, state="down"))
            else:
                rows.extend(_rows_from_status(name, st, now))
            continue
        reader = readers.setdefault(src, TailReader(src))
        state = states.setdefault(src, {})
        fold_records(state, reader.poll())
        if "stats" in state:
            rows.extend(_rows_from_status(src, state["stats"], now))
            continue
        slo = _fmt_slo(state.get("budgets"))
        sample = state.get("sample")
        if sample is None:
            rows.append(_row(src, slo=slo))
            continue
        t = sample.get("t")
        rows.append(_row(
            src,
            busy_frac=sample.get("busy_frac"),
            rss_bytes=sample.get("rss_bytes"),
            dev_in_use=sample.get("device_bytes_in_use"),
            dev_limit=sample.get("device_bytes_limit"),
            dev_peak=sample.get("device_peak_bytes"),
            compile_count=sample.get("compile_count"),
            compile_s=sample.get("compile_s_total"),
            slo=slo,
            age_s=(round(now - t, 1)
                   if isinstance(t, (int, float)) else None)))
    return rows


def _render_table(table: list) -> str:
    """Column-aligned plain text: first row is the header, first
    column left-justified, the rest right-justified."""
    widths = [max(len(row[i]) for row in table)
              for i in range(len(table[0]))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(
            cell.ljust(w) if j == 0 else cell.rjust(w)
            for j, (cell, w) in enumerate(zip(row, widths))).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def render_rows(rows: list) -> str:
    """The table: one header + one line per row, plain text."""
    table = [list(COLUMNS)]
    for r in rows:
        dev = ("-" if r["dev_in_use"] is None
               else _fmt_bytes(r["dev_in_use"])
               + ("/" + _fmt_bytes(r["dev_limit"])
                  if r["dev_limit"] is not None else ""))
        compile_ = ("-" if r["compile_count"] is None
                    else f"{r['compile_count']}"
                    + (f" ({r['compile_s']:.1f}s)"
                       if r["compile_s"] is not None else ""))
        name = r["name"]
        if r.get("state") not in (None, "up", "fitting", "idle",
                                  "done"):
            name += f" [{r['state']}]"
        table.append([
            name, _fmt_pct(r["busy_frac"]),
            _fmt_bytes(r["rss_bytes"]), dev,
            _fmt_bytes(r["dev_peak"]), compile_,
            "-" if r["queue_depth"] is None else str(r["queue_depth"]),
            ("-" if r["fits_per_hour"] is None
             else f"{r['fits_per_hour']:.0f}"),
            r.get("slo") or "-",
            _fmt_age(r["age_s"])])
    return _render_table(table)


def collect_tenant_rows(sources: list, readers: dict,
                        states: dict) -> list:
    """One poll over all sources → per-(tenant, priority class)
    usage rows (``--tenants``).  ``tenant_usage`` records are
    cumulative ledger snapshots, so the newest per key wins; a
    ``usage`` mapping in a ``/status`` body (``telemetry.report``
    shape) merges the same way."""
    usage: dict = {}
    for src in sources:
        if src.startswith(("http://", "https://")):
            st = fetch_source(src)
            if isinstance(st, dict) and isinstance(st.get("usage"),
                                                   dict):
                for key, v in st["usage"].items():
                    if isinstance(v, dict):
                        usage[key] = v
            continue
        reader = readers.setdefault(src, TailReader(src))
        state = states.setdefault(src, {})
        fold_records(state, reader.poll())
        usage.update(state.get("usage") or {})
    return [{"key": key, "fits": v.get("fits"),
             "busy_s": v.get("busy_s"), "sheds": v.get("sheds"),
             "violations": v.get("violations")}
            for key, v in sorted(usage.items())]


def render_tenant_rows(rows: list) -> str:
    """The ``--tenants`` table: one line per (tenant, class)."""
    table = [list(TENANT_COLUMNS)]
    for r in rows:
        table.append([
            r["key"],
            "-" if r["fits"] is None else str(r["fits"]),
            "-" if r["busy_s"] is None else f"{r['busy_s']:.1f}",
            "-" if r["sheds"] is None else str(r["sheds"]),
            "-" if r["violations"] is None else str(r["violations"])])
    return _render_table(table)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.telemetry.top",
        description="per-worker fleet resource columns from /status "
                    "endpoints or telemetry JSONL streams")
    parser.add_argument("sources", nargs="+",
                        help="status URLs (http://host:port/status) "
                             "and/or telemetry .jsonl paths")
    parser.add_argument("--follow", action="store_true",
                        help="redraw every --interval seconds")
    parser.add_argument("--once", action="store_true",
                        help="print one table and exit (default)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds (--follow)")
    parser.add_argument("--json", action="store_true",
                        help="emit rows as a JSON list, not a table")
    parser.add_argument("--tenants", action="store_true",
                        help="per-(tenant, class) usage rows instead "
                             "of per-worker resource rows")
    parser.add_argument("--max-frames", type=int, default=None,
                        help=argparse.SUPPRESS)   # test hook
    args = parser.parse_args(argv)

    readers: dict = {}
    states: dict = {}

    def frame() -> str:
        if args.tenants:
            rows = collect_tenant_rows(args.sources, readers, states)
            render = render_tenant_rows
        else:
            rows = collect_rows(args.sources, readers, states)
            render = render_rows
        if args.json:
            return json.dumps(rows, indent=1)
        return render(rows)

    if args.once or not args.follow:
        print(frame())
        return 0
    frames = 0
    try:
        while args.max_frames is None or frames < args.max_frames:
            out = frame()
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J" + out + "\n")
            else:
                sys.stdout.write(out + "\n\n")
            sys.stdout.flush()
            frames += 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":                           # pragma: no cover
    sys.exit(main())
