"""In-graph scalar taps: metrics out of a running ``lax.scan``.

The fast paths compile whole fits into single XLA programs
(``optim/adam.py``'s segment scan, ``inference/hmc.py``'s sampler), so
nothing host-side sees the loss evolve — a 5000-step fit is opaque
until it returns.  A :class:`ScalarTap` punches a throttled hole in
that wall with ``jax.debug.callback``:

* **static throttle** — ``log_every`` is a Python int baked into the
  trace, so the emit condition is a ``lax.cond`` on ``step %
  log_every == 0``; enabling a tap changes the traced program ONCE
  (one extra cached build) and adds zero retraces afterwards — the
  same executable serves every segment and every repeat fit.
* **unordered callbacks** — taps use the effect machinery
  ``jax.debug.print`` uses; XLA may run the callback concurrently
  with downstream compute, so the device never stalls on the host
  writing a JSON line.
* **rank-gated** — under multi-host SPMD every process executes the
  program; the host-side callback drops records on every process but
  0 (all hosts see identical replicated values, so one copy is the
  whole truth).  Inside a ``shard_map`` block pass ``gate=`` (e.g.
  ``axis_index == 0``) so only one *shard*'s callback fires.

Values are emitted as-is: scalars become floats, batched fits'
per-member vectors (e.g. a ``(n_starts,)`` loss) become lists.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

__all__ = ["ScalarTap", "make_tap", "batch_norm"]


def batch_norm(x):
    """L2 norm over the trailing (parameter) axis — scalar for a 1-D
    vector, per-member vector for a batched ``(K, ndim)`` fit."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    return jnp.sqrt(jnp.sum(x * x, axis=-1))


def _host_value(v):
    arr = np.asarray(v)
    if arr.ndim == 0:
        return float(arr)
    return [float(x) for x in arr.ravel()]


class ScalarTap:
    """Throttled in-graph scalar emitter bound to a MetricsLogger.

    Parameters
    ----------
    logger : MetricsLogger
        Destination of the emitted records (event = ``name``).
    name : str
        Record event name (``"adam"``, ``"hmc"``, ...).
    log_every : int
        Emit every ``log_every``-th step (static: part of the traced
        program — see module docstring).

    A tap is part of the cache key of any program built around it, and
    hashes/compares by ``(logger identity, name, log_every)`` — so two
    fits with the same logger and tap config share ONE compiled
    executable (zero retraces across repeat fits), while changing
    ``log_every`` (a different traced program) correctly builds anew.
    The cached program's closure keeps its tap — and through it the
    logger — alive, so the identity key can never alias a collected
    logger.
    """

    def __init__(self, logger, name: str = "fit", log_every: int = 50):
        if log_every < 1:
            raise ValueError(f"log_every must be >= 1, got {log_every}")
        self.logger = logger
        self.name = name
        self.log_every = int(log_every)

    def _key(self):
        return (id(self.logger), self.name, self.log_every)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, ScalarTap) and self._key() == other._key()

    def _callback(self, names, step, *values):
        import jax

        if jax.process_index() != 0:
            return
        self.logger.log(self.name, step=int(np.asarray(step)),
                        **{n: _host_value(v)
                           for n, v in zip(names, values)})

    def maybe_emit(self, step, scalars: dict, gate=None):
        """Traced: emit ``scalars`` iff ``step % log_every == 0``.

        Call from inside jit/scan/shard_map.  ``step`` is the global
        step index (traced or concrete); ``scalars`` maps field names
        to traced arrays; ``gate`` is an optional extra traced-bool
        predicate (e.g. ``axis_index == 0`` inside shard_map, so one
        shard speaks for the replicated values).
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        step = jnp.asarray(step)
        pred = (step % self.log_every) == 0
        if gate is not None:
            pred = jnp.logical_and(pred, gate)
        names = tuple(scalars)
        cb = functools.partial(self._callback, names)

        def _emit(args):
            jax.debug.callback(cb, *args)
            return ()

        def _skip(args):
            return ()

        lax.cond(pred, _emit, _skip,
                 (step,) + tuple(jnp.asarray(v)
                                 for v in scalars.values()))


def make_tap(telemetry, name: str, log_every: int) -> Optional[ScalarTap]:
    """The wiring convention every fit entry point shares: a tap
    exists iff a logger was passed AND ``log_every > 0``."""
    if telemetry is None or not log_every:
        return None
    return ScalarTap(telemetry, name=name, log_every=log_every)
