"""Static cost model: FLOPs, transcendentals, bytes from a jaxpr.

The perf-attribution counterpart of :mod:`.comm`: where the comm
counter proves what a program *communicates*, this module accounts
what it *computes* and *touches* — per program execution, from an
abstract trace, with zero device FLOPs.  The accounting walks the
same nested-jaxpr artifact the shard-safety analyzer uses
(:func:`multigrad_tpu.analysis.jaxprs.walk_eqns`, scan-trip
multipliers included), so "the SMF step runs N·E erf forward and N·E
exp backward" (BENCH_NOTES §2's hand arithmetic) becomes a machine
check instead of a margin note.

Three layers:

* :func:`estimate_program_cost` / :func:`model_cost` — trace a
  callable (or a model's SPMD program) and fold its equations into a
  :class:`ProgramCost`: weighted FLOPs, per-primitive transcendental
  element counts, argument/constant/output bytes, and the collective
  payload (via the analyzer's ``CollectiveSite`` collection, weighed
  by the shared :func:`.comm.leaf_nbytes` rule).
* :func:`predicted_time_s` — the roofline fold: ``max(flops / peak,
  bytes / bandwidth)`` against a per-backend :data:`DEVICE_SPECS`
  entry (the TPU v5e numbers are BENCH_NOTES §2's envelope estimate;
  treat the CPU entry as order-of-magnitude).
* :func:`roofline_record` — the telemetry-ready join against a
  *measured* time: "model says 1.1e7 erf + 48 B/step; chip delivered
  X% of roofline", as one flat record (:mod:`.profile` and
  ``bench.py`` emit it).

Counting conventions (deliberately simple, stated so the numbers are
interpretable): elementwise primitives cost 1 flop per output
element; transcendentals are weighted by their f32 lowering cost
(erf ≈ 15 — the 12-term rational polynomial + divide; exp ≈ 10 with
range reduction — BENCH_NOTES §2); ``dot_general`` costs
``2·out·contract``; reductions cost their input size; pure data
movement costs 0.  Shapes inside ``shard_map`` bodies are PER-SHARD,
so a distributed model's cost is per device — which is exactly the
denominator a per-chip roofline wants.  ``while`` trip counts are
dynamic; their bodies count once and ``has_dynamic_trips`` is set.

Module-level imports stay jax/numpy/stdlib + intra-telemetry (the
package contract); the analyzer plumbing is imported lazily inside
the functions that trace.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from .comm import leaf_nbytes

__all__ = ["ProgramCost", "estimate_program_cost", "model_cost",
           "DEVICE_SPECS", "SLOW_AXES", "device_spec",
           "predicted_time_s", "roofline_record",
           "TRANSCENDENTAL_FLOPS"]

# f32 lowering cost per element (BENCH_NOTES §2's conversion rates;
# the exact weights matter far less than keeping transcendentals an
# order of magnitude above FMAs).
TRANSCENDENTAL_FLOPS: Dict[str, float] = {
    "erf": 15.0, "erfc": 15.0, "erf_inv": 20.0,
    "exp": 10.0, "exp2": 10.0, "expm1": 10.0,
    "log": 10.0, "log2": 10.0, "log1p": 10.0, "logistic": 12.0,
    "tanh": 15.0, "sinh": 15.0, "cosh": 15.0,
    "sin": 10.0, "cos": 10.0, "tan": 20.0,
    "asin": 20.0, "acos": 20.0, "atan": 20.0, "atan2": 20.0,
    "pow": 15.0, "cbrt": 10.0, "lgamma": 30.0, "digamma": 30.0,
}

# Narrow-unit but non-transcendental ops (issue off the FMA pipe).
_CHEAP_FLOPS: Dict[str, float] = {
    "div": 4.0, "rem": 4.0, "sqrt": 2.0, "rsqrt": 2.0,
    "integer_pow": 2.0,
}

# Pure data movement: 0 flops (bytes are accounted separately).
_ZERO_FLOP = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "squeeze", "expand_dims", "iota", "copy", "device_put",
    "convert_element_type", "bitcast_convert_type", "gather",
    "stop_gradient", "split", "pvary", "pbroadcast",
})

# Reductions cost one op per INPUT element.
_REDUCE_PREFIXES = ("reduce_", "cum", "argmax", "argmin")


def _n_elements(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 1
    return int(np.prod(shape, dtype=np.int64))


def _eqn_out_elements(eqn) -> int:
    return max((_n_elements(v.aval) for v in eqn.outvars
                if hasattr(v, "aval")), default=1)


def _eqn_in_elements(eqn) -> int:
    return sum(_n_elements(v.aval) for v in eqn.invars
               if hasattr(v, "aval"))


def _dot_general_flops(eqn) -> float:
    """2 · out_elements · contraction_size (the classic matmul count)."""
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    contract = int(np.prod([lhs[d] for d in lhs_contract],
                           dtype=np.int64)) or 1
    return 2.0 * _eqn_out_elements(eqn) * contract


@dataclass
class ProgramCost:
    """Static per-execution cost of one traced program.

    ``flops`` is the weighted total (transcendental weights applied);
    ``transcendentals`` maps primitive name → element count (the
    BENCH_NOTES-§2 quantity: ``cost.transcendentals["erf"] == N·E``
    for the SMF step).  ``arg_bytes``/``const_bytes``/``out_bytes``
    are the program's input/captured/output footprints —
    ``min_hbm_bytes`` (their sum) is the fused ideal of one read per
    input and one write per output; a fwd+bwd program that re-reads
    its inputs in the backward pays up to 2× the input side (the SMF
    step's measured ~8 MB vs a 4 MB catalog, BENCH_NOTES §2).
    ``comm_bytes``/``comm_calls`` reuse the analyzer's collective
    collection — the (|y|+|params|)·itemsize claim rides here.
    """

    flops: float = 0.0
    transcendentals: Dict[str, int] = field(default_factory=dict)
    flops_by_prim: Dict[str, float] = field(default_factory=dict)
    arg_bytes: int = 0
    const_bytes: int = 0
    out_bytes: int = 0
    comm_bytes: int = 0
    comm_calls: int = 0
    #: Collective payload split by the mesh axis it crosses (psum
    #: ``axes`` / all_gather ``axis_name`` read off the trace) — the
    #: sharded-K accounting: on a 2-level (replica, data) mesh this
    #: separates the fast data-axis traffic from anything crossing
    #: the slow replica axis, so :func:`predicted_time_s` can cover
    #: K-sharded programs.  A site naming several axes contributes
    #: its payload to each (it crosses each link).
    comm_bytes_by_axis: Dict[str, int] = field(default_factory=dict)
    #: Payload at sites whose axis names were not recoverable
    #: (positional axes, exotic primitives) — folded against the
    #: fast link so no traffic silently drops out of the prediction.
    comm_bytes_unattributed: int = 0
    has_dynamic_trips: bool = False

    @property
    def transcendental_total(self) -> int:
        return int(sum(self.transcendentals.values()))

    @property
    def min_hbm_bytes(self) -> int:
        return int(self.arg_bytes + self.const_bytes + self.out_bytes)

    def record(self, top: int = 6) -> dict:
        """Flat telemetry-ready summary (``costmodel`` event body)."""
        prims = sorted(self.flops_by_prim.items(),
                       key=lambda kv: -kv[1])[:top]
        return {
            "flops": float(self.flops),
            "transcendentals": {k: int(v) for k, v
                                in self.transcendentals.items()},
            "transcendental_total": self.transcendental_total,
            "top_flop_prims": {k: float(v) for k, v in prims},
            "arg_bytes": int(self.arg_bytes),
            "const_bytes": int(self.const_bytes),
            "out_bytes": int(self.out_bytes),
            "min_hbm_bytes": self.min_hbm_bytes,
            "comm_bytes": int(self.comm_bytes),
            "comm_calls": int(self.comm_calls),
            "comm_bytes_by_axis": {k: int(v) for k, v in
                                   self.comm_bytes_by_axis.items()},
            "comm_bytes_unattributed":
                int(self.comm_bytes_unattributed),
            "has_dynamic_trips": bool(self.has_dynamic_trips),
        }


def _cost_of_closed(closed) -> ProgramCost:
    from ..analysis.jaxprs import (CALLBACK_PRIMS, COLLECTIVE_PRIMS,
                                   collect_collectives, iter_consts,
                                   subjaxprs, walk_eqns)

    cost = ProgramCost()
    for eqn, _path, mult in walk_eqns(closed):
        name = eqn.primitive.name
        if name == "while":
            cost.has_dynamic_trips = True
        if subjaxprs(eqn):
            continue          # container: its body is walked separately
        if name in COLLECTIVE_PRIMS or name in CALLBACK_PRIMS \
                or name in _ZERO_FLOP:
            continue
        if name in TRANSCENDENTAL_FLOPS:
            elems = _eqn_out_elements(eqn) * mult
            cost.transcendentals[name] = \
                cost.transcendentals.get(name, 0) + elems
            flops = elems * TRANSCENDENTAL_FLOPS[name]
        elif name == "dot_general":
            flops = _dot_general_flops(eqn) * mult
        elif name.startswith(_REDUCE_PREFIXES):
            flops = _eqn_in_elements(eqn) * mult
        elif name in _CHEAP_FLOPS:
            flops = _eqn_out_elements(eqn) * _CHEAP_FLOPS[name] * mult
        else:
            flops = _eqn_out_elements(eqn) * mult
        cost.flops += flops
        cost.flops_by_prim[name] = \
            cost.flops_by_prim.get(name, 0.0) + flops

    jaxpr = getattr(closed, "jaxpr", closed)
    cost.arg_bytes = sum(leaf_nbytes(v.aval) for v in jaxpr.invars
                         if hasattr(v, "aval"))
    cost.out_bytes = sum(leaf_nbytes(v.aval) for v in jaxpr.outvars
                         if hasattr(v, "aval"))
    cost.const_bytes = sum(leaf_nbytes(c) for c, _ in
                           iter_consts(closed))
    sites = collect_collectives(closed)
    cost.comm_bytes = sum(s.executed_bytes for s in sites)
    cost.comm_calls = sum(s.mult for s in sites)
    for s in sites:
        if not s.axes:
            cost.comm_bytes_unattributed += s.executed_bytes
            continue
        for axis in s.axes:
            cost.comm_bytes_by_axis[axis] = \
                cost.comm_bytes_by_axis.get(axis, 0) \
                + s.executed_bytes
    return cost


def estimate_program_cost(fn, *args) -> ProgramCost:
    """Trace ``fn(*args)`` abstractly and account its cost.

    ``args`` may mix concrete arrays, ``ShapeDtypeStruct``\\ s and
    pytrees thereof (same contract as the analyzer's
    ``trace_program``).  Nothing executes; the trace is the analysis
    artifact.
    """
    import jax

    from ..analysis.jaxprs import abstractify, trace_program

    args = jax.tree_util.tree_map(abstractify, args)
    return _cost_of_closed(trace_program(fn, *args))


def model_cost(model, params, kind: str = "loss_and_grad",
               randkey=None) -> ProgramCost:
    """Cost of ONE execution of a model's SPMD program.

    Builds a fresh program for ``kind`` (any of
    ``OnePointModel._build_local_fn``'s kinds) exactly like
    :func:`.comm.measure_model_comm` and accounts it.  For the
    paper's headline ``"loss_and_grad"`` program on the SMF model
    this reproduces BENCH_NOTES §2: ``transcendentals["erf"] == N·E``
    (forward), ``transcendentals["exp"] == N·E`` (backward), and
    ``comm_bytes == (|y| + |params|) · 4`` on a distributed comm.
    Shapes inside ``shard_map`` are per-shard, so distributed
    models report per-device cost (the per-chip roofline
    denominator).
    """
    import jax
    import jax.numpy as jnp

    with_key = randkey is not None
    program = model._build_program(kind, with_key)
    if with_key:
        from ..optim.adam import init_randkey
        key = init_randkey(randkey)
    else:
        key = jnp.zeros(())
    params = jnp.asarray(params, dtype=jnp.result_type(float)) \
        if not hasattr(params, "dtype") else params
    return estimate_program_cost(
        program, jax.ShapeDtypeStruct(np.shape(params), params.dtype),
        model.aux_leaves(), key)


# ------------------------------------------------------------------ #
# Roofline prediction
# ------------------------------------------------------------------ #
# Per-backend peak envelopes.  The TPU v5e vector numbers are
# BENCH_NOTES §2's estimate ((8×128) lanes × 4-deep SIMD × 2
# flop/FMA at 0.94 GHz ≈ 7.7e12 f32 vector flop/s; ~819 GB/s HBM) —
# the right denominator for the erf/exp-heavy fits this repo runs
# (the MXU's matmul peak is irrelevant to them).  The CPU entry is
# an order-of-magnitude single-socket envelope; override per call
# when you know your host.
#: ``interconnect_bytes_per_s`` is the per-device collective-link
#: envelope (ICI for TPUs, shared-memory copies for the CPU mesh)
#: the comm term of :func:`predicted_time_s` folds against — needed
#: once sharded-K programs carry (K/R)-scaled payloads that grow
#: with the bucket size.  ``slow_axis_bytes_per_s`` is the DCN-class
#: envelope applied to axes named in ``slow_axes`` (the 2-level
#: meshes' outer axis names), which the sharded-K design keeps
#: traffic-free during fits.
DEVICE_SPECS: Dict[str, dict] = {
    "tpu v5": {"flops_per_s": 7.7e12, "hbm_bytes_per_s": 8.19e11,
               "interconnect_bytes_per_s": 9.0e10,
               "slow_axis_bytes_per_s": 6.25e9,
               "source": "BENCH_NOTES §2 VPU envelope / v5e HBM"},
    "tpu": {"flops_per_s": 7.7e12, "hbm_bytes_per_s": 8.19e11,
            "interconnect_bytes_per_s": 9.0e10,
            "slow_axis_bytes_per_s": 6.25e9,
            "source": "v5e defaults (override for other generations)"},
    "cpu": {"flops_per_s": 1.0e11, "hbm_bytes_per_s": 3.0e10,
            "interconnect_bytes_per_s": 1.0e10,
            "slow_axis_bytes_per_s": 1.0e10,
            "source": "order-of-magnitude host envelope"},
}

#: Mesh axis names treated as the slow (DCN-class) link by the comm
#: fold: the outer axes of the shipped 2-level layouts
#: (:func:`~multigrad_tpu.parallel.hybrid_mesh` /
#: :func:`~multigrad_tpu.parallel.ensemble_mesh`).
SLOW_AXES = ("hosts", "replica")


def device_spec(device_kind: Optional[str] = None) -> dict:
    """The :data:`DEVICE_SPECS` entry for a device kind (longest
    matching key, case-insensitive; default: the current backend's
    first device)."""
    if device_kind is None:
        import jax
        try:
            device_kind = jax.devices()[0].device_kind
        except (RuntimeError, IndexError):
            device_kind = "cpu"
    kind = str(device_kind).lower()
    best = None
    for key, spec in DEVICE_SPECS.items():
        if key in kind and (best is None or len(key) > len(best)):
            best = key
    spec = dict(DEVICE_SPECS[best or "cpu"])
    spec["device_kind"] = str(device_kind)
    return spec


def predicted_time_s(cost: ProgramCost, spec: Optional[dict] = None,
                     device_kind: Optional[str] = None) -> dict:
    """Roofline fold of a :class:`ProgramCost`.

    ``predicted_s = max(compute_s, memory_s, comm_s)`` with ``bound``
    naming the binding side.  The memory side uses ``min_hbm_bytes``
    — the one-read-one-write ideal — so the prediction is a *lower*
    bound on the achievable time; "X% of roofline" read off a
    measurement is then honest (it can only flatter the hardware,
    never the code).

    The comm side folds each mesh axis's payload
    (``cost.comm_bytes_by_axis``) against the interconnect envelope
    — ``slow_axis_bytes_per_s`` for :data:`SLOW_AXES` (DCN-class
    outer axes of the 2-level meshes), ``interconnect_bytes_per_s``
    otherwise — which is what makes the prediction meaningful for
    sharded-K programs, whose data-axis payload scales with the
    bucket/ensemble width K/R (the term the bucket-ladder tuner's
    static prune ranks the larger rungs by).  Payload at a site
    without recoverable axis names falls back to the fast link.
    """
    spec = spec or device_spec(device_kind)
    compute_s = cost.flops / spec["flops_per_s"]
    memory_s = cost.min_hbm_bytes / spec["hbm_bytes_per_s"]
    fast_bw = spec.get("interconnect_bytes_per_s")
    comm_s = 0.0
    if fast_bw:
        slow_bw = spec.get("slow_axis_bytes_per_s", fast_bw)
        for axis, nbytes in cost.comm_bytes_by_axis.items():
            comm_s += nbytes / (slow_bw if axis in SLOW_AXES
                                else fast_bw)
        comm_s += cost.comm_bytes_unattributed / fast_bw
    predicted = max(compute_s, memory_s, comm_s)
    bound = "compute"
    if predicted == memory_s and memory_s > compute_s:
        bound = "memory"
    if predicted == comm_s and comm_s > max(compute_s, memory_s):
        bound = "comm"
    return {
        "compute_s": compute_s,
        "memory_s": memory_s,
        "comm_s": comm_s,
        "predicted_s": predicted,
        "bound": bound,
        "device_kind": spec.get("device_kind"),
        "spec_source": spec.get("source"),
    }


def roofline_record(cost: ProgramCost, measured_s: float,
                    spec: Optional[dict] = None,
                    device_kind: Optional[str] = None,
                    **extra) -> dict:
    """The attribution join: model-predicted vs measured time.

    Returns the flat ``roofline`` telemetry record — "model says
    1.1e7 erf + 48 B/step; chip delivered X% of roofline" — where
    ``roofline_frac = predicted_s / measured_s`` (1.0 = the hardware
    envelope, small = the program left the chip idle).  ``extra``
    fields (config name, steps) ride along.
    """
    pred = predicted_time_s(cost, spec=spec, device_kind=device_kind)
    rec = dict(pred)
    rec.update(cost.record())
    rec["measured_s"] = float(measured_s)
    rec["roofline_frac"] = (
        float(pred["predicted_s"] / measured_s)
        if measured_s and measured_s > 0 else None)
    rec.update(extra)
    return rec
